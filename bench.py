"""Benchmark driver — prints ONE JSON line.

Primary metric (BASELINE.md): cold-pull→HBM wall-clock / MB/s/chip sustained.

This driver times the DELIVERY side of the system; its twin
``tools/bench_serve.py`` (same one-JSON-line contract) times the SERVE
side — hot-hit re-serving from a warm store through the bounded session
pool. Run both to cover the two halves of the north star.

This drives the REAL pipeline end-to-end, staging the north-star scenario
("cold-pull→HBM from a warm peer, ≥3× faster than hf-cli + restore"):

  setup   a loopback fake HF hub serves a synthetic multi-shard bf16
          safetensors checkpoint; a *peer node* pulls it warm (untimed) and
          serves its content-addressed store over the native /peer API;
  ours    a cold node pulls the model with the peer configured
          (registry walk → peer DCN fetch → C++ chunk store → HBM sink:
          per-tensor range reads → `jax.device_put` under a NamedSharding)
          — timed start→arrays-on-device;
  control the `huggingface-cli + restore` analogue: stream the same files
          from the hub to disk, read them back whole, parse, `device_put`
          — timed the same way.

`vs_baseline` = control/ours speedup (>1 means we beat the baseline path).
Falls back to a pure device-ingest microbench if the native plane cannot
build (keeps the driver's bench step alive on a broken toolchain).

Env knobs: DEMODEL_BENCH_MB (default 256), DEMODEL_BENCH_SHARDS (default 4).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from http.server import ThreadingHTTPServer
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))  # tests/ holds the fake-hub fixture

TOTAL_MB = int(os.environ.get("DEMODEL_BENCH_MB", "256"))
N_SHARDS = int(os.environ.get("DEMODEL_BENCH_SHARDS", "4"))
MODEL = "bench/llama-synthetic"


def _build_repo(total_mb: int, n_shards: int) -> dict[str, bytes]:
    """filename → bytes: an n-shard bf16 checkpoint of ~total_mb MB."""
    import ml_dtypes

    from demodel_tpu.formats import safetensors as st

    cols = 4096
    rows = total_mb * (1 << 20) // 2 // n_shards // 2 // cols  # 2 tensors/shard
    files: dict[str, bytes] = {
        "config.json": json.dumps({"model_type": "llama", "hidden_size": cols}).encode(),
    }
    weight_map: dict[str, str] = {}
    rng = np.random.default_rng(0)
    for i in range(n_shards):
        fname = f"model-{i + 1:05d}-of-{n_shards:05d}.safetensors"
        tensors = {}
        for j in range(2):
            name = f"blocks.{i}.w{j}"
            tensors[name] = rng.standard_normal((rows, cols), np.float32).astype(
                ml_dtypes.bfloat16
            )
            weight_map[name] = fname
        files[fname] = st.serialize(tensors)
    files["model.safetensors.index.json"] = json.dumps(
        {"metadata": {}, "weight_map": weight_map}
    ).encode()
    return files


def _force_cpu_if_asked() -> None:
    """DEMODEL_BENCH_CPU=1 pins the bench to the CPU backend — the only
    reliable switch (a sitecustomize registers the TPU backend before any
    env var is read). For smoke-testing bench logic while the tunnel is
    down; the driver's real runs never set it."""
    if os.environ.get("DEMODEL_BENCH_CPU", "").strip() == "1":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass


def _bench_e2e() -> dict:
    # validate BEFORE the expensive timed section: a typo'd strategy must
    # fail at startup, not after minutes of e2e pulls
    strategy = os.environ.get("DEMODEL_BENCH_STRATEGY", "sharded").strip()
    if strategy not in ("file", "sharded"):
        raise SystemExit(
            f"DEMODEL_BENCH_STRATEGY={strategy!r}: must be 'file' or "
            "'sharded' — a mislabeled strategy would poison the "
            "regression anchors")
    _force_cpu_if_asked()
    import jax

    from demodel_tpu.config import ProxyConfig
    from demodel_tpu.delivery import pull
    from demodel_tpu.formats import safetensors as st  # noqa: F401 (control path)
    from demodel_tpu.proxy import ProxyServer
    from tests.fake_registries import make_hf_handler

    import requests

    repo_files = _build_repo(TOTAL_MB, N_SHARDS)
    weight_bytes = sum(
        len(v) for k, v in repo_files.items() if k.endswith(".safetensors")
    )

    with tempfile.TemporaryDirectory() as td:
        tmp = Path(td)
        hub = ThreadingHTTPServer(
            ("127.0.0.1", 0), make_hf_handler({MODEL: repo_files})
        )
        import threading

        threading.Thread(target=hub.serve_forever, daemon=True).start()
        endpoint = f"http://127.0.0.1:{hub.server_address[1]}"

        def node_cfg(name: str) -> ProxyConfig:
            # no_mitm: the bench never MITMs (direct HTTP to the fake hub,
            # /peer serving) — skipping leaf minting keeps the whole e2e
            # leg dep-light (no `cryptography`), so the host-RAM degrade
            # leg can land a datapoint on minimal hosts too
            return ProxyConfig(
                host="127.0.0.1", port=0, mitm_hosts=[], no_mitm=True,
                cache_dir=tmp / f"{name}-cache", data_dir=tmp / f"{name}-data",
                use_ecdsa=True,
            )

        try:
            # ---- warm the peer (untimed) and serve its store over /peer
            cfg_a = node_cfg("peer")
            pull(MODEL, cfg_a, endpoint=endpoint)
            with ProxyServer(cfg_a, verbose=False) as peer_node:
                # warm up jax (compile/alloc/dtype paths) before timing —
                # both contenders transfer bf16, so neither pays first-use
                # setup inside its window
                import ml_dtypes as _md

                jax.block_until_ready(
                    jax.device_put(np.zeros((1024, 1024), np.float32))
                )
                jax.block_until_ready(
                    jax.device_put(np.zeros((256, 4096), _md.bfloat16))
                )
                import ml_dtypes as _md2  # local name for the probe below

                def _link_probe() -> float:
                    """Raw host→device rate for one 64 MB device_put.
                    Called AFTER both delivery legs — by then the tunnel
                    burst buffer has drained through 2× checkpoint bytes,
                    so this reads the SUSTAINED channel rate the bulk of
                    a large transfer faces (probing before the legs would
                    both steal the headline leg's burst headroom and
                    report the burst rate, inverting the diagnosis)."""
                    probe = np.zeros((8192, 4096), _md2.bfloat16)
                    t0 = time.perf_counter()
                    jax.block_until_ready(jax.device_put(probe))
                    rate = round(
                        probe.nbytes / 1e6 / (time.perf_counter() - t0), 1)
                    print(f"[bench] sustained link probe: {rate} MB/s "
                          "host→device", file=sys.stderr)
                    return rate

                # ---- ours: cold node, warm peer → HBM, best of two
                # strategies (both legitimate cold pulls):
                #   whole-file — streaming pull: files land in host buffers
                #     over multi-stream fetch, tensors stream to device,
                #     cache persistence continues off-clock;
                #   sharded — manifest-ordered window reads straight off
                #     the peer into per-tensor landing buffers
                #     (sink/remote.py): tensor N+1's fetch overlaps tensor
                #     N's host→device transfer, zero disk/hash on-clock.
                from demodel_tpu.delivery import pull_to_hbm
                from demodel_tpu.sink.remote import pull_manifest_to_hbm

                # RSS accounting for the north-star-scale mode: baseline
                # after jax warmup; peak measured after the strategy legs.
                # The first leg's placement is freed before the second so
                # the peak bounds ONE checkpoint + delivery buffers, not
                # two checkpoints
                import resource

                def _vm_rss_kb() -> int:
                    # CURRENT RSS, not ru_maxrss: the high-water mark
                    # never decreases, so a transient early peak (repo
                    # serialization, warmup) would inflate the baseline
                    # and make the ceiling assertion vacuous
                    with open("/proc/self/status") as f:
                        for line in f:
                            if line.startswith("VmRSS:"):
                                return int(line.split()[1])
                    return 0

                rss0_kb = _vm_rss_kb()

                # correctness oracle inputs captured up front
                blob = repo_files[f"model-00001-of-{N_SHARDS:05d}.safetensors"]
                spec = st.parse_header(blob).tensors["blocks.0.w0"]
                src = spec.to_numpy(blob[spec.start:spec.end])

                def leg_file() -> tuple[float, float, dict]:
                    t0 = time.perf_counter()
                    report, placed = pull_to_hbm(
                        MODEL, node_cfg("cold"), endpoint=endpoint,
                        peers=[peer_node.url], defer_cache_commit=True,
                    )
                    secs = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    placed.finalize()
                    fin_secs = time.perf_counter() - t0
                    assert placed is not None \
                        and len(placed.arrays) == 2 * N_SHARDS
                    got = np.asarray(placed.arrays["blocks.0.w0"])
                    if not np.array_equal(got, src):
                        raise AssertionError(
                            "delivered tensor != source bytes")
                    del got, placed  # free before the next leg (RSS bound)
                    return secs, fin_secs, report

                def leg_sharded() -> tuple[float, dict]:
                    t0 = time.perf_counter()
                    report_sh, placed_sh = pull_manifest_to_hbm(
                        MODEL, [peer_node.url])
                    secs = time.perf_counter() - t0
                    assert len(placed_sh.arrays) == 2 * N_SHARDS
                    got_sh = np.asarray(placed_sh.arrays["blocks.0.w0"])
                    del placed_sh
                    if not np.array_equal(got_sh, src):
                        raise AssertionError(
                            "sharded delivery != source bytes")
                    del got_sh
                    return secs, report_sh

                # the HEADLINE strategy runs FIRST: host→device bandwidth
                # through a tunneled backend is state-dependent (a burst
                # buffer absorbs the first ~GB fast, then drains to the
                # sustained rate), so whichever leg runs first is
                # systematically favored — that must be the strategy on
                # the record, not the alternate
                if strategy == "file":
                    ours_file, finalize_secs, report = leg_file()
                    ours_sharded, report_sh = leg_sharded()
                else:
                    ours_sharded, report_sh = leg_sharded()
                    ours_file, finalize_secs, report = leg_file()
                rss_peak_kb = resource.getrusage(
                    resource.RUSAGE_SELF).ru_maxrss
                # headline strategy is PRE-SELECTED per configuration
                # (validated at function entry), not a per-run min of two
                # attempts: min-of-two vs a single-sample control would
                # bias the recorded ratio and every regression anchor
                # derived from it (advisor r4). The sharded manifest pull
                # is the flagship path; DEMODEL_BENCH_STRATEGY=file
                # headlines whole-file instead.
                ours = ours_file if strategy == "file" else ours_sharded
                link_mbps = _link_probe()
                print(f"[bench] ours: whole-file={ours_file:.3f}s "
                      f"sharded={ours_sharded:.3f}s → headline strategy: "
                      f"{strategy}", file=sys.stderr)
                if os.environ.get("DEMODEL_BENCH_PROFILE"):
                    print(f"[profile] whole-file={ours_file:.3f}s "
                          f"pull={report.get('secs')}s "
                          f"sink={report.get('tpu_sink', {}).get('secs')}s "
                          f"finalize(untimed)={finalize_secs:.3f}s "
                          f"files={[round(f['secs'], 3) for f in report['files']]} "
                          f"sharded={report_sh.get('secs')}s "
                          f"net={report_sh.get('network_bytes')}B",
                          file=sys.stderr)

                # RSS ceiling (VERDICT r4 weak #3): on the CPU backend
                # "device memory" is host RAM, and a landed tensor is
                # resident ~twice at peak (numpy landing buffer + device
                # buffer) — measured ~1.8× landed bytes at 2 GiB. The
                # ceiling (2× + 512 MB slack) catches the failure mode
                # that matters: naive whole-FILE buffering adds ANOTHER
                # full checkpoint (≥3×). Enforced only at scale (≥1 GiB)
                # where it means something; override via
                # DEMODEL_BENCH_RSS_CEILING_MB.
                rss_delta_mb = (rss_peak_kb - rss0_kb) >> 10
                ceiling_mb = int(os.environ.get(
                    "DEMODEL_BENCH_RSS_CEILING_MB",
                    str(int(TOTAL_MB * 2.0 + 512))))
                if TOTAL_MB >= 1024 and rss_delta_mb > ceiling_mb:
                    raise AssertionError(
                        f"peak RSS grew {rss_delta_mb} MB for a "
                        f"{TOTAL_MB} MB checkpoint (ceiling {ceiling_mb})")
                print(f"[bench] rss: +{rss_delta_mb} MB "
                      f"(ceiling {ceiling_mb} MB at scale)", file=sys.stderr)

            # ---- control: hub → disk → parse → device. Two flavors
            # (VERDICT r4 weak #5: the in-process simulation alone can't
            # back the literal ≥3× north-star claim):
            #   real — the ACTUAL `huggingface-cli download` binary on
            #     the clock (HF_ENDPOINT at the fake hub), then parse +
            #     device_put in-process; used for vs_baseline whenever
            #     the binary exists.
            #   sim — the in-process analogue (kept for environments
            #     without the CLI and for continuity with r01-r04
            #     anchors; recorded as control_sim_secs either way).
            import shutil as _shutil
            import subprocess as _sp

            names = [n for n in repo_files if n.endswith(".safetensors")]

            def _parse_and_place(dl) -> float:
                arrs = []
                for name in names:
                    blob = (dl / name.replace("/", "_")).read_bytes()
                    idx = st.parse_header(blob)
                    for spec in idx.tensors.values():
                        arrs.append(jax.device_put(
                            spec.to_numpy(blob[spec.start:spec.end])))
                jax.block_until_ready(arrs)

            dl = tmp / "control"
            dl.mkdir()
            t0 = time.perf_counter()
            sess = requests.Session()
            for name in ["config.json", "model.safetensors.index.json"] + names:
                r = sess.get(f"{endpoint}/{MODEL}/resolve/main/{name}", stream=True)
                r.raise_for_status()
                with open(dl / name.replace("/", "_"), "wb") as f:
                    for chunk in r.iter_content(1 << 20):
                        f.write(chunk)
            _parse_and_place(dl)
            control_sim = time.perf_counter() - t0

            control_real = None
            hf_cli = _shutil.which("huggingface-cli")
            if hf_cli and not os.environ.get("DEMODEL_BENCH_NO_REAL_CONTROL"):
                dl2 = tmp / "control-real"
                env = dict(os.environ)
                env.update({"HF_ENDPOINT": endpoint,
                            "HF_HOME": str(tmp / "hf-home"),
                            "HF_HUB_DISABLE_TELEMETRY": "1",
                            "HF_HUB_DISABLE_XET": "1",
                            "HF_HUB_DISABLE_PROGRESS_BARS": "1"})
                t0 = time.perf_counter()
                try:
                    r = _sp.run([hf_cli, "download", MODEL,
                                 "--local-dir", str(dl2)],
                                env=env, capture_output=True, text=True,
                                timeout=3600)
                except _sp.TimeoutExpired:
                    # a wedged CLI must not sink the whole run after the
                    # expensive "ours" legs — sim control still stands
                    r = None
                    print("[bench] real control timed out — falling back "
                          "to sim control", file=sys.stderr)
                if r is not None and r.returncode == 0:
                    # hf-cli keeps hub-style paths; flatten like _parse
                    # expects
                    for name in names:
                        p = dl2 / name
                        if p.exists() and "/" in name:
                            p.rename(dl2 / name.replace("/", "_"))
                    _parse_and_place(dl2)
                    control_real = time.perf_counter() - t0
                elif r is not None:
                    print(f"[bench] real control failed "
                          f"(rc={r.returncode}): {r.stderr[-300:]} — "
                          "falling back to sim control", file=sys.stderr)
            control = control_real if control_real is not None else control_sim
            print(f"[bench] control: real="
                  f"{'n/a' if control_real is None else round(control_real, 3)}s "
                  f"sim={control_sim:.3f}s", file=sys.stderr)
        finally:
            hub.shutdown()

    mb = weight_bytes / 1e6
    return {
        "metric": "cold_pull_to_hbm_throughput",
        "value": round(mb / ours, 2),
        "unit": "MB/s/chip",
        "vs_baseline": round(control / ours, 3),
        # both strategies on the record (the headline is one, fixed above)
        "strategy": strategy,
        "whole_file_mbps": round(mb / ours_file, 2),
        "sharded_mbps": round(mb / ours_sharded, 2),
        "rss_delta_mb": rss_delta_mb,
        "link_sustained_mbps": link_mbps,
        # which control stack vs_baseline came from, + both on record
        "control": "real-hf-cli" if control_real is not None else "sim",
        "control_sim_secs": round(control_sim, 3),
        **({"control_real_secs": round(control_real, 3)}
           if control_real is not None else {}),
        # sharded-leg phase split (fetch vs device-place vs final block):
        # the network-bound / transfer-bound diagnosis for slow pulls —
        # on a tunneled backend these differ by 10× and name the culprit.
        # Emitted UNCONDITIONALLY: PROFILE_r05's diagnosis flow keys on
        # this field, and an absent split is indistinguishable from a
        # driver that forgot to record it ({} = the leg reported no split)
        "sharded_phase_secs": report_sh.get("phase_secs") or {},
        **({"sharded_block_secs": report_sh["block_secs"]}
           if report_sh.get("block_secs") is not None else {}),
        # north-star projection: BASELINE.md's Llama-2-7B is ~13 GB —
        # the <30s cold-pull→HBM goal at this run's measured rate
        "projected_13gb_s": round(13000 / (mb / ours), 1),
    }


# ---------------------------------------------------------------- fallback


def _bench_fallback() -> dict:
    """Pure device-ingest microbench (no native plane): streamed device_put
    vs write-to-disk-then-load, same shapes as the e2e bench."""
    _force_cpu_if_asked()
    import jax

    rng = np.random.default_rng(0)
    host = [
        rng.standard_normal((TOTAL_MB * (1 << 20) // 2 // 16 // 4096, 4096), np.float32)
        for _ in range(16)
    ]
    dev = jax.devices()[0]
    jax.block_until_ready(jax.device_put(host[0], dev))
    t0 = time.perf_counter()
    jax.block_until_ready([jax.device_put(h, dev) for h in host])
    ours = time.perf_counter() - t0

    with tempfile.NamedTemporaryFile(delete=False) as f:
        path = f.name
    try:
        t0 = time.perf_counter()
        with open(path, "wb") as f:
            for h in host:
                f.write(h.tobytes())
        with open(path, "rb") as f:
            blobs = [
                np.frombuffer(f.read(h.nbytes), dtype=h.dtype).reshape(h.shape)
                for h in host
            ]
        jax.block_until_ready([jax.device_put(b, dev) for b in blobs])
        control = time.perf_counter() - t0
    finally:
        os.unlink(path)
    mb = sum(h.nbytes for h in host) / 1e6
    return {
        # distinct metric name: a degraded run must not masquerade as e2e
        "metric": "device_ingest_throughput_fallback",
        "value": round(mb / ours, 2),
        "unit": "MB/s/chip",
        "vs_baseline": round(control / ours, 3),
    }


def _check_regression(out: dict) -> dict:
    """Perf regression gate (VERDICT r2 #1, anchor fixed per VERDICT r3 #2):
    compare against the most recent recorded round whose metric MATCHES —
    skipping outage/fallback rounds (e.g. ``bench_unavailable_*``), which
    previously lost the anchor and shipped r04 with no ``vs_prev`` at all.
    Also reports ``vs_best`` against the best matching round ever recorded.
    A drop >10% vs either anchor is flagged loudly on stderr and in the
    JSON — a regressed number must never ship silently again."""
    try:
        anchors = []  # (filename, value), oldest → newest, matching metric only
        for pf in sorted(REPO.glob("BENCH_r*.json")):
            try:
                prev = json.loads(pf.read_text()).get("parsed", {})
            except ValueError:
                continue
            if prev.get("metric") == out["metric"] and prev.get("value", 0) > 0:
                anchors.append((pf.name, float(prev["value"])))
        if not anchors:
            return out
        prev_name, prev_val = anchors[-1]
        best_name, best_val = max(anchors, key=lambda a: a[1])
        out["vs_prev"] = round(out["value"] / prev_val, 3)
        out["vs_best"] = round(out["value"] / best_val, 3)
        if out["value"] < 0.9 * prev_val:
            out["regressed"] = True
            print(f"PERF REGRESSION: {out['value']} {out['unit']} < "
                  f"last matching round's {prev_val} ({prev_name})",
                  file=sys.stderr)
        elif out["value"] < 0.9 * best_val:
            out["regressed_vs_best"] = True
            print(f"PERF below best-ever: {out['value']} {out['unit']} < "
                  f"{best_val} ({best_name})", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the gate must not kill the bench
        print(f"regression check skipped: {e}", file=sys.stderr)
    return out


def _archive_history_check(out: dict) -> None:
    """Post-run proof for the degraded leg: the pull's own telemetry
    survived into the on-disk archive and comes back over the restore
    server's ``/debug/telemetry/history`` endpoint — the retention-plane
    datapoint rides the bench line instead of needing its own driver."""
    import http.client

    if not os.environ.get("DEMODEL_TELEMETRY_ARCHIVE"):
        return
    try:
        from demodel_tpu.utils import retention

        archive = retention.ensure()
        if archive is not None:
            archive.flush_once()  # the windows the flusher hasn't reached
        from demodel_tpu.restore.server import RestoreRegistry, RestoreServer
        from demodel_tpu.store import Store

        with tempfile.TemporaryDirectory() as td:
            with RestoreServer(RestoreRegistry(Store(Path(td) / "s")),
                               host="127.0.0.1") as srv:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=30)
                try:
                    conn.request(
                        "GET",
                        "/debug/telemetry/history?family=pull_bytes_total",
                        headers={"Connection": "close"})
                    doc = json.loads(conn.getresponse().read())
                finally:
                    conn.close()
        pts = doc.get("series", {}).get("pull_bytes_total", [])
        out["telemetry_history_points"] = len(pts)
        if not pts:
            out["telemetry_history_error"] = \
                "history endpoint returned no pull_bytes_total series"
    except Exception as e:  # noqa: BLE001 — the check must not kill the leg
        out["telemetry_history_error"] = str(e)


def _run_guarded(kind: str, timeout: int) -> dict | None:
    """Run one bench leg in a subprocess with a hard timeout.

    The TPU tunnel (axon) can wedge so that jax backend init blocks
    forever inside ``make_c_api_client`` — uninterruptible from Python.
    The driver must still get its ONE JSON line, so each leg runs in a
    killable child."""
    proc = subprocess.run(
        [sys.executable, __file__, f"--{kind}-child"],
        capture_output=True, text=True, timeout=timeout,
    )
    sys.stderr.write(proc.stderr[-4000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                return parsed
        except ValueError:
            continue
    return None


def main():
    if "--e2e-child" in sys.argv:
        print(json.dumps(_bench_e2e()))
        return
    if "--e2e-hostram-child" in sys.argv:
        # device-unreachable degrade (ROADMAP: the north-star metric was
        # dark for three rounds while the tunnel was wedged): pin jax to
        # the CPU backend so "HBM" is host RAM, but run the FULL pull
        # pipeline — registry walk, peer DCN fetch, native store, sink
        # range reads, device_put — so the datapoint still moves with the
        # delivery plane. Recorded under its own metric name: a degraded
        # round must never masquerade as (or anchor against) the real
        # device-side series.
        os.environ["DEMODEL_BENCH_CPU"] = "1"
        # the degraded leg doubles as the retention-plane datapoint: the
        # pull runs with the archive on, and the history endpoint must
        # hand the pull's own series back after the fact
        os.environ.setdefault(
            "DEMODEL_TELEMETRY_ARCHIVE",
            str(Path(tempfile.mkdtemp(prefix="bench-telarch-"))))
        from demodel_tpu.utils import retention

        retention.ensure()
        out = _bench_e2e()
        out["metric"] = "cold_pull_to_host_ram_throughput"
        out["degraded_reason"] = "device_unreachable"
        out["projected_13gb_s"] = None  # projection is a device-side claim
        _archive_history_check(out)
        print(json.dumps(out))
        return
    if "--fallback-child" in sys.argv:
        print(json.dumps(_bench_fallback()))
        return
    if "--probe-child" in sys.argv:
        _force_cpu_if_asked()
        import jax

        print(json.dumps({"metric": "probe", "value": 1.0,
                          "unit": str(jax.devices()[0]),
                          "vs_baseline": 0.0}))
        return
    # fail fast on a wedged tunnel: a cheap backend-init probe first, so
    # the driver waits ~4 min for the truthful unavailable line instead
    # of the full e2e+fallback timeout ladder (~25 min)
    try:
        probe = _run_guarded("probe", 270)
    except Exception:  # noqa: BLE001 — any probe failure means unreachable
        probe = None
    if probe is None:
        # degrade, don't go dark: the host-RAM sink leg exercises the full
        # pull pipeline on the CPU backend so every round still lands a
        # real delivery-plane datapoint (own metric name + regression
        # anchors; see --e2e-hostram-child above)
        print("device probe failed (wedged TPU tunnel?); degrading to the "
              "host-RAM sink leg", file=sys.stderr)
        try:
            out = _run_guarded("e2e-hostram", 1200)
        except Exception as e:  # noqa: BLE001 — bench must print a line
            print(f"host-RAM leg failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            out = None
        if out is not None:
            print(json.dumps(_check_regression(out)))
            return
        print("host-RAM leg produced no result; reporting unavailable",
              file=sys.stderr)
        print(json.dumps({
            "metric": "bench_unavailable_device_unreachable",
            "value": 0.0, "unit": "MB/s/chip", "vs_baseline": 0.0,
        }))
        return
    for kind, timeout in (("e2e", 1200), ("fallback", 300)):
        try:
            out = _run_guarded(kind, timeout)
            if out is not None:
                print(json.dumps(_check_regression(out)))
                return
            print(f"{kind} bench produced no result; degrading",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"{kind} bench timed out (wedged TPU tunnel?); degrading",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — bench must always print a line
            print(f"{kind} bench failed ({type(e).__name__}: {e}); degrading",
                  file=sys.stderr)
    # truthful last resort: record that the device was unreachable rather
    # than hanging the driver or faking a number
    print(json.dumps({
        "metric": "bench_unavailable_device_unreachable",
        "value": 0.0, "unit": "MB/s/chip", "vs_baseline": 0.0,
    }))


if __name__ == "__main__":
    main()
