"""demodel-tpu: TPU-native caching/syncing/distributing middleware for
models and datasets — capability rebuild of the reference MITM proxy
(CA lifecycle + selective interception + content-addressed cache) with a
TPU delivery stack on top (streamed HBM placement, peer DCN cache,
Orbax-compatible network restore)."""

__version__ = "0.3.0"
