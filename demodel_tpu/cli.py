"""CLI — parity with the reference's cobra surface (``cmd/demodel/main.go:56-81``):

- ``demodel-tpu``            — bare invocation runs the server (ref ``main.go:68-70``)
- ``demodel-tpu start``      — run the MITM caching proxy (ref ``start.go:218-230``)
- ``demodel-tpu init``       — materialize the CA once (ref ``init.go:156-168``)
- ``demodel-tpu export-ca``  — print CA PEM / inject into trust stores
  (ref ``export_ca.go:22-120``), incl. the ``openssl`` preset the reference
  README documents but never implemented (``README.md:50``, SURVEY.md §5)
- ``demodel-tpu pull``       — north-star addition: pull a model through the
  cache with ``--sink=tpu`` landing shards in HBM.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from demodel_tpu.config import ProxyConfig


def _cmd_init(cfg: ProxyConfig, args) -> int:
    # PKI (and its `cryptography` dependency) loads only for the commands
    # that mint/export certificates — `start --no-mitm`/`serve`/peer nodes
    # stay dep-light
    from demodel_tpu import pki

    ca = pki.read_or_new_ca(cfg.data_dir, use_ecdsa=cfg.use_ecdsa)
    cert_path, _ = pki.ca_paths(cfg.data_dir)
    print(f"CA ready at {cert_path}", file=sys.stderr)
    assert ca.cert_pem
    install_system_trust(cert_path.read_bytes())
    return 0


def install_system_trust(pem: bytes) -> bool:
    """Install the CA into the OS trust store so clients using system roots
    (curl, git-lfs, …) trust the proxy without per-tool flags.

    The reference attempts this via ``smallstep/truststore``
    (``init.go:145-148``) — with a pwd-relative-filename bug that makes the
    first run fail (SURVEY.md §5); we implement the intended behavior:
    Debian-style ``/usr/local/share/ca-certificates`` + a best-effort
    ``update-ca-certificates``, failure-as-warning, never fatal.
    ``DEMODEL_TRUST_DIR`` overrides the target (tests, non-root installs).
    """
    import os

    trust_dir = Path(os.environ.get(
        "DEMODEL_TRUST_DIR", "/usr/local/share/ca-certificates"))
    target = trust_dir / "demodel-tpu-ca.crt"
    try:
        trust_dir.mkdir(parents=True, exist_ok=True)
        target.write_bytes(pem)
    except OSError as e:
        print(f"trust-store: cannot write {target} ({e}); "
              "run as root or use `export-ca`", file=sys.stderr)
        return False
    try:
        subprocess.run(["update-ca-certificates"], capture_output=True,
                       text=True, check=True, timeout=60)
        print(f"trust-store: installed {target} (system bundle updated)",
              file=sys.stderr)
        return True
    except FileNotFoundError:
        print(f"trust-store: wrote {target}; update-ca-certificates not "
              "found — refresh the bundle with your distro's tool",
              file=sys.stderr)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        print(f"trust-store: wrote {target}; bundle refresh failed: {e}",
              file=sys.stderr)
    return False


def _cmd_export_ca(cfg: ProxyConfig, args) -> int:
    from demodel_tpu import pki

    cert_path, _ = pki.ca_paths(cfg.data_dir)
    if not cert_path.exists():
        print("CA not initialized; run `demodel-tpu init` first", file=sys.stderr)
        return 1
    pem = cert_path.read_bytes()
    if not args.for_:
        sys.stdout.write(pem.decode())
        return 0
    for preset in args.for_:
        if preset == "python-ssl":
            _export_python_ssl(pem)
        elif preset == "python-certifi":
            _export_python_certifi(pem)
        elif preset == "openssl":
            _export_openssl(pem)
        else:
            print(f"unknown --for preset: {preset}", file=sys.stderr)
            return 1
    return 0


def _export_python_ssl(pem: bytes) -> None:
    """Write the CA into ssl's default capath (ref ``export_ca.go:51-86``,
    which shells out to python; we *are* python, so query ssl directly)."""
    import ssl

    paths = ssl.get_default_verify_paths()
    capath = paths.capath or (Path(paths.cafile).parent if paths.cafile else None)
    if capath is None:
        print("python-ssl: no capath/cafile reported by ssl", file=sys.stderr)
        return
    target = Path(capath) / "demodel-ca.crt"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_bytes(pem)
    print(f"python-ssl: wrote {target}", file=sys.stderr)


def _export_python_certifi(pem: bytes) -> None:
    """Append the CA to certifi's bundle (ref ``export_ca.go:87-103``). Unlike
    the reference we skip the append if already present (idempotent)."""
    try:
        import certifi
    except ImportError:
        print("python-certifi: certifi not installed", file=sys.stderr)
        return
    bundle = Path(certifi.where())
    existing = bundle.read_bytes()
    if pem.strip() in existing:
        print(f"python-certifi: already present in {bundle}", file=sys.stderr)
        return
    with open(bundle, "ab") as f:
        f.write(b"\n" + pem)
    print(f"python-certifi: appended to {bundle}", file=sys.stderr)


def _export_openssl(pem: bytes) -> None:
    """The preset the reference documents but doesn't implement
    (``README.md:50`` vs ``export_ca.go:104-105``): install into OPENSSLDIR
    with a subject-hash symlink so `openssl verify`/libssl pick it up."""
    try:
        out = subprocess.run(
            ["openssl", "version", "-d"], capture_output=True, text=True, check=True
        ).stdout
    except (OSError, subprocess.CalledProcessError) as e:
        print(f"openssl: cannot locate OPENSSLDIR: {e}", file=sys.stderr)
        return
    # OPENSSLDIR: "/usr/lib/ssl"
    ssl_dir = out.split(":", 1)[1].strip().strip('"')
    certs = Path(ssl_dir) / "certs"
    target = certs / "demodel-ca.crt"
    try:
        certs.mkdir(parents=True, exist_ok=True)
        target.write_bytes(pem)
        h = subprocess.run(
            ["openssl", "x509", "-subject_hash", "-noout"],
            input=pem, capture_output=True, check=True,
        ).stdout.decode().strip()
        link = certs / f"{h}.0"
        if not link.exists():
            link.symlink_to(target.name)
        print(f"openssl: installed {target} ({link.name})", file=sys.stderr)
    except (OSError, subprocess.CalledProcessError) as e:
        print(f"openssl: install failed (need root?): {e}", file=sys.stderr)


def _cmd_start(cfg: ProxyConfig, args) -> int:
    from demodel_tpu.proxy import ProxyServer

    # getattr: bare `demodel-tpu` (no subcommand) routes here with the
    # root-parser namespace, which has no serve_* attributes
    server = ProxyServer(cfg,
                         session_threads=getattr(args, "serve_threads", None),
                         session_queue=getattr(args, "serve_queue", None))
    server.start()
    print(
        f"demodel-tpu proxy listening on {cfg.host}:{cfg.port} "
        f"(mitm_all={cfg.mitm_all} no_mitm={cfg.no_mitm} hosts={cfg.mitm_hosts})",
        file=sys.stderr,
    )
    try:
        server.wait()
    except KeyboardInterrupt:
        server.stop()
    return 0


def _cmd_pull(cfg: ProxyConfig, args) -> int:
    from demodel_tpu.delivery import pull

    try:
        if getattr(args, "sharded", False):
            # pod shape: shard-reads straight off a warm peer's manifest —
            # each host fetches only its devices' byte windows (DCN) and
            # replicated tensors complete over ICI (sink/remote.py)
            if not args.peer:
                print("--sharded requires at least one --peer",
                      file=sys.stderr)
                return 2
            from demodel_tpu.sink.remote import pull_manifest_to_hbm

            report, _placed = pull_manifest_to_hbm(
                args.model, args.peer, source=args.source)
        else:
            report = pull(
                args.model,
                cfg,
                source=args.source,
                sink=args.sink,
                revision=args.revision,
                peers=args.peer or None,
            )
    except Exception as e:  # noqa: BLE001 — CLI boundary: no raw tracebacks
        print(f"pull failed: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, indent=2, default=str))
    return 0


def _cmd_gc(cfg: ProxyConfig, args) -> int:
    """One-shot store GC to the given (or env) cap — operability for
    long-lived nodes without restarting the proxy."""
    from demodel_tpu.delivery import open_store
    from demodel_tpu.utils.env import env_int

    max_gb = args.max_gb or env_int("DEMODEL_CACHE_MAX_GB", 0)
    if max_gb <= 0:
        print("gc: no cap given (--max-gb or DEMODEL_CACHE_MAX_GB)",
              file=sys.stderr)
        return 2
    store = open_store(cfg)
    try:
        total, freed, evicted = store.gc(max_gb << 30)
    finally:
        store.close()
    print(json.dumps({"cap_gb": max_gb, "in_use_bytes": total,
                      "freed_bytes": freed, "evicted": evicted}))
    return 0


def _cmd_serve(cfg: ProxyConfig, args) -> int:
    """Run the full node: MITM caching proxy (with native /peer endpoints)
    plus the /restore API over the same store."""
    from demodel_tpu.delivery import open_store
    from demodel_tpu.proxy import ProxyServer
    from demodel_tpu.restore.server import RestoreRegistry, RestoreServer

    proxy = ProxyServer(cfg,
                        session_threads=getattr(args, "serve_threads", None),
                        session_queue=getattr(args, "serve_queue", None))
    proxy.start()
    store = restore = None
    try:
        store = open_store(cfg)
        registry = RestoreRegistry(store)
        # tensor BYTES serve from the C++ plane on the proxy port; the
        # Python server remains the control plane (manifests, models, PUT)
        registry.attach_native(proxy)
        restore = RestoreServer(registry, host=cfg.host,
                                port=args.restore_port, proxy=proxy)
        restore.start()
        print(
            f"demodel-tpu node: proxy+peer on {cfg.host}:{proxy.port}, "
            f"restore API + /metrics on {cfg.host}:{restore.port}",
            file=sys.stderr,
        )
        proxy.wait()
    except KeyboardInterrupt:
        pass
    finally:
        if restore is not None:
            restore.stop()
        proxy.stop()
        if store is not None:
            store.close()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="demodel-tpu",
        description="Caching, syncing, distributing middleware for models and "
        "datasets — TPU-native. Bare invocation starts the proxy.",
    )
    sub = p.add_subparsers(dest="cmd")
    st = sub.add_parser("start", help="run the MITM caching proxy")
    sub.add_parser("init", help="create the root CA")
    e = sub.add_parser("export-ca", help="export/install the root CA")
    e.add_argument("--for", dest="for_", action="append", default=[],
                   choices=["python-ssl", "python-certifi", "openssl"],
                   help="trust-store preset (repeatable)")
    pl = sub.add_parser("pull", help="pull a model through the cache")
    pl.add_argument("model")
    pl.add_argument("--source", default="hf", choices=["hf", "ollama"])
    pl.add_argument("--sink", default="cache", choices=["cache", "tpu"])
    pl.add_argument("--revision", default="main")
    pl.add_argument("--peer", action="append", default=[],
                    help="peer node base URL tried before upstream (repeatable)")
    pl.add_argument("--sharded", action="store_true",
                    help="pod pull: read only this host's shard windows "
                         "off a warm peer's manifest, straight to HBM "
                         "(implies --sink=tpu; requires --peer)")
    sv = sub.add_parser("serve", help="run proxy + peer + restore APIs")
    sv.add_argument("--restore-port", type=int, default=8081)
    for serving in (st, sv):
        # bounded session executor (see README "Serve-plane tuning"):
        # explicit flag > DEMODEL_PROXY_THREADS/_QUEUE env > 2×CPUs auto
        serving.add_argument("--serve-threads", type=int, default=None,
                             help="session worker pool size "
                                  "(default: DEMODEL_PROXY_THREADS or 2×CPUs)")
        serving.add_argument("--serve-queue", type=int, default=None,
                             help="accept-queue bound; overflow is answered "
                                  "503 + Retry-After (default: "
                                  "DEMODEL_PROXY_QUEUE or 4×pool)")
    g = sub.add_parser("gc", help="evict LRU cache entries to a size cap")
    g.add_argument("--max-gb", type=int, default=0)
    mf = sub.add_parser(
        "manifest",
        help="synthesize a model manifest from the proxy-warmed cache "
             "(lets a foreign-client-warmed node seed pod pulls/restore)")
    mf.add_argument("model")
    mf.add_argument("--source", default="hf", choices=["hf", "ollama"])
    mf.add_argument(
        "--include-private", action="store_true",
        help="explicitly republish auth-scoped (gated-repo) cache "
             "entries under public peer-servable keys; without this, "
             "gated bytes are omitted from the synthesized manifest")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cfg = ProxyConfig.from_env()
    cmd = args.cmd or "start"  # bare root runs the server (main.go:68-70)
    if cmd == "init":
        return _cmd_init(cfg, args)
    if cmd == "export-ca":
        return _cmd_export_ca(cfg, args)
    if cmd == "pull":
        return _cmd_pull(cfg, args)
    if cmd == "serve":
        return _cmd_serve(cfg, args)
    if cmd == "gc":
        return _cmd_gc(cfg, args)
    if cmd == "manifest":
        from demodel_tpu.delivery import open_store, synthesize_manifest

        store = open_store(cfg)
        try:
            record = synthesize_manifest(
                store, args.model, source=args.source,
                include_private=args.include_private)
        except (FileNotFoundError, PermissionError) as e:
            print(str(e), file=sys.stderr)
            return 1
        finally:
            store.close()
        print(json.dumps(record, indent=2))
        return 0
    return _cmd_start(cfg, args)


if __name__ == "__main__":
    sys.exit(main())
