"""Configuration — reference env-var semantics, bugs fixed.

The reference parses five ``DEMODEL_PROXY_*`` vars at package init
(``cmd/demodel/main.go:23-42``) with a latent bug: with
``DEMODEL_PROXY_MITM_HOSTS`` *unset*, ``strings.Split("", ",")`` yields
``[""]`` which clobbers the default host list, so out of the box nothing is
intercepted (SURVEY.md §5). This rebuild implements the *intended*
semantics: defaults apply when the env is unset; set-but-empty clears.

Paths follow XDG (successor of ``adrg/xdg`` / the legacy ``directories``
crate): data (CA material) under ``$XDG_DATA_HOME/demodel-tpu``, cache
(store root) under ``$XDG_CACHE_HOME/demodel-tpu``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path

from demodel_tpu.utils.env import env_bool, env_int

#: reference default MITM target list (``main.go:38-42``)
DEFAULT_MITM_HOSTS = ["huggingface.co:443"]


def xdg_data_home() -> Path:
    return Path(os.environ.get("XDG_DATA_HOME",
                               Path.home() / ".local" / "share"))


def xdg_cache_home() -> Path:
    return Path(os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache"))


def default_data_dir() -> Path:
    return xdg_data_home() / "demodel-tpu"


def default_cache_dir() -> Path:
    return xdg_cache_home() / "demodel-tpu"


@dataclass
class ProxyConfig:
    host: str = "0.0.0.0"
    port: int = 8080  # reference listens on :8080 (``start.go:206``)
    mitm_all: bool = False
    no_mitm: bool = False
    mitm_hosts: list[str] = field(
        default_factory=lambda: list(DEFAULT_MITM_HOSTS))
    use_ecdsa: bool = False  # reference default is RSA (sic, 4095-bit)
    cache_enabled: bool = True
    data_dir: Path = field(default_factory=default_data_dir)
    cache_dir: Path = field(default_factory=default_cache_dir)
    #: extra CA bundle for verifying UPSTREAM servers (tests, corp proxies)
    upstream_ca: str | None = None

    def __post_init__(self) -> None:
        self.data_dir = Path(self.data_dir)
        self.cache_dir = Path(self.cache_dir)

    def should_mitm(self, authority: str) -> bool:
        """Connect-policy parity with ``start.go:183-196`` — ``no_mitm``
        wins, then ``mitm_all``, then the exact ``host:port`` list."""
        if self.no_mitm:
            return False
        if self.mitm_all:
            return True
        return authority in self.mitm_hosts

    @classmethod
    def from_env(cls) -> "ProxyConfig":
        cfg = cls(
            host=os.environ.get("DEMODEL_PROXY_HOST", "0.0.0.0"),
            port=env_int("DEMODEL_PROXY_PORT", 8080),
            mitm_all=env_bool("DEMODEL_PROXY_MITM_ALL"),
            no_mitm=env_bool("DEMODEL_PROXY_NO_MITM"),
            use_ecdsa=env_bool("DEMODEL_PROXY_CA_USE_ECDSA"),
        )
        # intended semantics: unset → defaults survive; set → replace
        # (empty string clears); EXTRA_HOSTS always extends
        hosts_env = os.environ.get("DEMODEL_PROXY_MITM_HOSTS")
        if hosts_env is not None:
            cfg.mitm_hosts = [h.strip() for h in hosts_env.split(",")
                              if h.strip()]
        extra = os.environ.get("DEMODEL_PROXY_MITM_EXTRA_HOSTS", "")
        cfg.mitm_hosts += [h.strip() for h in extra.split(",") if h.strip()]
        if "DEMODEL_DATA_DIR" in os.environ:
            cfg.data_dir = Path(os.environ["DEMODEL_DATA_DIR"])
        if "DEMODEL_CACHE_DIR" in os.environ:
            cfg.cache_dir = Path(os.environ["DEMODEL_CACHE_DIR"])
        if "DEMODEL_UPSTREAM_CA" in os.environ:
            cfg.upstream_ca = os.environ["DEMODEL_UPSTREAM_CA"]
        return cfg
