"""Pull orchestration: registry → store → (snapshot dir | TPU HBM).

This is the north-star delivery layer (``BASELINE.json`` ``north_star``): the
reference stops at cached bytes on disk; the rebuild can additionally land a
pulled checkpoint directly in device memory under a ``NamedSharding``
(``sink="tpu"``, see :mod:`demodel_tpu.sink`).
"""

from __future__ import annotations

import errno
import json
import os
import time
from pathlib import Path

from demodel_tpu.config import ProxyConfig
from demodel_tpu.registry.base import PullReport
from demodel_tpu.store import Store
from demodel_tpu.utils import metrics
from demodel_tpu.utils.logging import get_logger

log = get_logger("delivery")


def open_store(cfg: ProxyConfig) -> Store:
    """The delivery client and the MITM proxy share one store root, so a
    model pulled by either path is a cache hit for the other."""
    return Store(cfg.cache_dir / "proxy")


def manifest_key(source: str, model: str) -> str:
    """Store key of a pulled model's manifest record (lets any node —
    including a peer that syncs the record — re-materialize or serve the
    model without re-walking the registry)."""
    from demodel_tpu.store import key_for_uri

    return key_for_uri(f"demodel://models/{source}/{model}")


def pull(
    model: str,
    cfg: ProxyConfig,
    source: str = "hf",
    sink: str = "cache",
    revision: str = "main",
    endpoint: str | None = None,
    store: Store | None = None,
    mesh=None,
    peers: list[str] | None = None,
) -> dict:
    """Pull ``model`` and deliver to the requested sink.

    sink="cache" → bytes live in the content-addressed store;
    sink="tpu"   → additionally stream tensors into device HBM under a
                   NamedSharding and report placement.
    ``peers`` — base URLs of peer nodes tried (over DCN) before upstream.
    """
    report, _ = pull_to_hbm(
        model, cfg, source=source, revision=revision, endpoint=endpoint,
        store=store, mesh=mesh, peers=peers, deliver=(sink == "tpu"),
    )
    return report


def _enforce_tier_budgets(store: Store) -> None:
    """Tier-budget-driven eviction after a pull (replaces the old
    ``_maybe_gc`` periodic sweep): the shared tier trims the host-RAM hot
    tier to its budget, then the disk tier to ``DEMODEL_CACHE_MAX_GB``
    (0 = unbounded) via :meth:`Store.gc` — pin shield and
    ``store_evictions_total`` semantics unchanged. The native proxy
    enforces the same disk knob on its serving loop; this covers
    first-party pull traffic."""
    from demodel_tpu import tier

    tier.shared(store).enforce()


def _persist_manifest(store: Store, mkey: str, out: dict,
                      failed_keys: set[str]) -> None:
    """Write the model-manifest record, omitting files whose cache commit
    failed (a durable manifest must never reference keys that aren't in the
    store — they would break later serve/materialize/peer-restore)."""
    rec = out
    if failed_keys:
        rec = dict(out)
        rec["files"] = [f for f in out["files"] if f["key"] not in failed_keys]
        log.warning("manifest omits %d files whose cache commit failed",
                    len(out["files"]) - len(rec["files"]))
    if store.has(mkey):
        store.remove(mkey)
    body = json.dumps(rec).encode()
    meta = {"kind": "model-manifest", "model": rec["name"],
            "source": rec["source"]}
    try:
        store.put(mkey, body, meta)
    except OSError as e:
        if e.errno != errno.ENOSPC:
            raise
        # full disk on the manifest landing: evict to budget and retry
        # once — a tiny JSON record almost always fits after a sweep. A
        # second ENOSPC degrades gracefully: the pulled bytes already
        # reached their sink; only the durable record (lazy-restore
        # registration, peer advertisement) is lost, which a re-pull or
        # synthesize_manifest() can rebuild — not worth failing the pull.
        _enforce_tier_budgets(store)
        try:
            store.put(mkey, body, meta)
        except OSError as e2:
            if e2.errno != errno.ENOSPC:
                raise
            log.warning("manifest for %s not persisted: disk full even "
                        "after eviction (%s)", rec["name"], e2)


def pull_to_hbm(
    model: str,
    cfg: ProxyConfig,
    source: str = "hf",
    revision: str = "main",
    endpoint: str | None = None,
    store: Store | None = None,
    mesh=None,
    peers: list[str] | None = None,
    deliver: bool = True,
    defer_cache_commit: bool = False,
):
    """Pull ``model`` and stream its weights into HBM as shards arrive.

    Fetch workers overlap with device landing (:mod:`demodel_tpu.sink.streaming`)
    so the wall-clock is max(network, host→device), not the sum. Returns
    ``(report_dict, Placement | None)`` — the placement holds the live
    sharded arrays.

    ``defer_cache_commit=True`` returns as soon as the placement is resident
    (the north-star clock): pending cache commits, the manifest record, and
    the store close (when owned) move to a background finalizer — join it
    via ``placement.finalize()`` before reading the store or exiting.
    """
    own_store = store is None
    if store is None:
        store = open_store(cfg)
    elif defer_cache_commit:
        # the background finalizer would run cache commits against a store
        # handle the caller could close first — a native use-after-free.
        # Deferred persistence is only safe when this function owns the
        # store lifecycle.
        raise ValueError("defer_cache_commit=True requires pull_to_hbm to "
                         "own the store (omit the store= argument)")
    peer_set = None
    if peers is None:
        peers = [p for p in os.environ.get("DEMODEL_PEERS", "").split(",") if p.strip()]
    if peers:
        from demodel_tpu.parallel.peer import PeerGossip, PeerSet

        peer_set = PeerSet(peers)
        # enroll the peer set for background index refresh: this pull's
        # locate calls (and every later pull's rotation build) answer
        # from gossiped possession data instead of per-pull probe rounds
        PeerGossip.shared().track(peers)
    sink_worker = None
    handed_off = False  # True once the background finalizer owns flush+close
    profile_dir = os.environ.get("DEMODEL_PROFILE_DIR", "").strip()
    profiling = False
    if profile_dir and deliver:
        # SURVEY §5 tracing: a jax.profiler trace around the delivery
        # window (fetch overlap + device_put stream) — open in xprof/
        # tensorboard to see host→device transfer occupancy
        try:
            import jax.profiler as _profiler

            _profiler.start_trace(profile_dir)
            profiling = True
        except Exception as e:  # noqa: BLE001 — tracing must never break a pull
            log.warning("jax.profiler trace not started: %s", e)
    t0 = time.perf_counter()
    try:
        buffer_budget = None
        if deliver:
            from demodel_tpu.sink.streaming import StreamingSink

            sink_worker = StreamingSink(store, mesh=mesh)
            buffer_budget = sink_worker.budget

        if sink_worker is not None:
            _sink = sink_worker

            def on_file(artifact):
                _sink.submit(artifact)
                # the sink queue (and the background cache commit) hold their
                # own buffer references; dropping this one keeps peak host
                # RAM at the in-flight window, not the whole model
                artifact.buffer = None
        else:
            on_file = None

        # memory-first delivery only when a sink consumes the buffers: peer
        # bytes land in host memory → HBM, the cache copy commits on a
        # background thread (disk never gates the cold-pull→HBM clock)
        memory_sink = deliver and peer_set is not None
        if source == "hf":
            from demodel_tpu.registry.hf import HFRegistry

            reg = HFRegistry(
                store,
                endpoint=endpoint or os.environ.get("HF_ENDPOINT", "https://huggingface.co"),
                token=os.environ.get("HF_TOKEN"),
                ca=cfg.upstream_ca,
                peers=peer_set,
                memory_sink=memory_sink,
                buffer_budget=buffer_budget,
            )
            report = reg.pull(model, revision=revision, on_file=on_file)
        elif source == "ollama":
            from demodel_tpu.registry.ollama import OllamaRegistry

            reg = OllamaRegistry(
                store,
                endpoint=endpoint or os.environ.get("OLLAMA_REGISTRY", "https://registry.ollama.ai"),
                ca=cfg.upstream_ca,
                peers=peer_set,
                memory_sink=memory_sink,
                buffer_budget=buffer_budget,
            )
            report = reg.pull(model, on_file=on_file)
        else:
            raise ValueError(f"unknown source {source!r}")

        out = report.to_dict()
        mkey = manifest_key(source, model)
        metrics.HUB.inc("pulls_total")
        metrics.HUB.inc("pull_bytes_total", report.total_bytes)
        metrics.HUB.inc("pull_files_from_peer_total",
                        sum(1 for f in report.files if f.from_peer))
        metrics.HUB.inc("pull_files_from_cache_total",
                        sum(1 for f in report.files if f.from_cache))
        placed = None
        if sink_worker is not None:
            placed = sink_worker.finish()
            sink_worker = None
            sink_secs = time.perf_counter() - t0
            out["tpu_sink"] = {
                "tensors": len(placed.arrays),
                "bytes": placed.total_bytes,
                "secs": round(sink_secs, 3),
                "mesh": str(placed.mesh_desc),
            }
            metrics.HUB.inc("sink_tensors_total", len(placed.arrays))
            metrics.HUB.inc("sink_bytes_total", placed.total_bytes)
            metrics.HUB.inc("sink_secs_total", sink_secs)
        if defer_cache_commit and placed is not None:
            # the north-star clock stops here — disk persistence (cache
            # commits + manifest) and the store close continue off it
            fetcher, close_store = reg.fetcher, own_store

            def _finalize():
                try:
                    fails = fetcher.flush_writes()
                    placed.commit_errors = fails
                    placed.integrity_errors = list(fetcher.integrity_failures)
                    _persist_manifest(store, mkey, out,
                                      {k for k, _ in fails})
                    _enforce_tier_budgets(store)
                except BaseException as e:  # noqa: BLE001 — surfaced at finalize()
                    placed.finalize_error = e
                finally:
                    if close_store:
                        store.close()

            import threading

            t = threading.Thread(target=_finalize, daemon=True,
                                 name="delivery-finalize")
            t.start()
            placed.finalizer = t
            handed_off = True
        else:
            # manifest only after every cache commit landed: a durable
            # record must not reference keys that never hit the store
            fails = reg.fetcher.flush_writes()
            _persist_manifest(store, mkey, out, {k for k, _ in fails})
            _enforce_tier_budgets(store)
            if reg.fetcher.integrity_failures:
                # optimistic verify found the delivered bytes corrupt —
                # the placement is poisoned; fail the pull
                raise IOError(
                    "peer bytes failed digest verification after delivery: "
                    f"{reg.fetcher.integrity_failures}")
        return out, placed
    finally:
        if profiling:
            try:
                import jax.profiler as _profiler

                _profiler.stop_trace()
                log.info("delivery trace written to %s", profile_dir)
            except Exception as e:  # noqa: BLE001
                log.warning("jax.profiler stop_trace failed: %s", e)
        if sink_worker is not None:  # pull raised — abandon delivery
            sink_worker.cancel()
        if not handed_off:
            # in-flight cache commits hold native pointers into the store —
            # closing it under them would be a use-after-free, so join them
            # before any close
            if "reg" in locals():
                reg.fetcher.flush_writes()
            if own_store:
                store.close()


def synthesize_manifest(store: Store, model: str, source: str = "hf",
                        persist: bool = True,
                        include_private: bool = False) -> dict:
    """Build a model-manifest record out of a PROXY-warmed cache — no
    first-party pull required.

    A peer whose store was populated by foreign clients through the MITM
    proxy (hf-cli, transformers, vLLM …) holds every byte of the model,
    but URL-keyed: full objects under their resolve/CDN URIs plus
    zero-byte LFS redirects carrying the content digest. This walks those
    entries for ``{model}/resolve/...`` URIs, publishes digest-located
    blobs under stable keys (hardlink, zero copy), and persists the same
    manifest record :func:`pull` writes — after which the peer can seed a
    sharded pod pull (`sink/remote.py`) or a restore registration exactly
    as if it had pulled first-party. Reference analogy: the proxy cache
    IS the source of truth ("proxied and cached, automatically",
    `/root/reference/CONTRIBUTING.md:51`); this makes its contents
    first-class.

    Raises ``FileNotFoundError`` when no cached files match ``model``.
    """
    import re as _re

    from demodel_tpu.store import key_for_uri

    if source == "ollama":
        return _synthesize_ollama_manifest(
            store, model, persist=persist, include_private=include_private)
    pat = _re.compile(
        _re.escape(model) + r"/resolve/([^/]+)/(.+)$")
    files: dict[str, dict] = {}  # filename → entry (first revision wins)
    skipped_private: list[str] = []
    for key in store.list():
        meta = store.meta(key) or {}
        uri = meta.get("uri", "")
        m = pat.search(uri.split("?", 1)[0])
        if not m:
            continue
        rev, name = m.group(1), m.group(2)
        status = int(meta.get("status", 200) or 200)
        headers = meta.get("headers", {}) or {}
        if 301 <= status <= 308:
            # LFS redirect stub: the content lives under the CDN URL /
            # digest link; publish it under a deterministic key
            linked = (headers.get("x-linked-etag", "") or "").strip('"')
            if len(linked) != 64 or not store.has_digest(linked):
                continue
            synth_key = key_for_uri(f"demodel://synth/{model}/{name}")
            if not store.has(synth_key):
                store.materialize(synth_key, linked, {
                    "uri": uri, "sha256": linked, "synthesized": True,
                })
            entry_key, sha = synth_key, linked
        elif status == 200 and store.size(key) > 0:
            entry_key, sha = key, meta.get("sha256", "")
            if store.is_private(key):
                # gated-repo entry (auth-scoped): the peer plane refuses
                # private keys, so a manifest referencing one would 404.
                # Republishing it under a public key makes the bytes
                # world-readable on the unauthenticated /peer plane —
                # that needs an explicit opt-in, not a side effect of
                # manifest synthesis (advisor r4, medium).
                if not include_private:
                    skipped_private.append(name)
                    continue
                entry_key = key_for_uri(f"demodel://synth/{model}/{name}")
                if not store.has(entry_key):
                    w = store.begin(entry_key)
                    try:
                        for chunk in store.stream(key):
                            w.append(chunk)
                        if sha and w.digest() != sha:
                            w.abort(keep_partial=False)
                            raise IOError(
                                f"cached {name} does not match its "
                                "recorded digest")
                        w.commit({"uri": uri, "sha256": sha or w.digest(),
                                  "synthesized": True})
                    except BaseException:
                        if w._open:  # noqa: SLF001 — writer state check
                            w.abort(keep_partial=False)
                        raise
        else:
            continue
        files.setdefault(name, {
            "name": name, "key": entry_key, "size": store.size(entry_key),
            "sha256": sha, "revision": rev, "media_type": "",
        })
    _WEIGHT_SUFFIXES = (".safetensors", ".bin", ".pt", ".pth", ".gguf",
                        ".onnx", ".msgpack", ".h5")
    # a gated copy of a file whose PUBLIC copy made it into the manifest
    # (repo un-gated later; two cached revisions) is not a loss at all
    skipped_private = [n for n in skipped_private if n not in files]
    skipped_weights = [n for n in skipped_private
                       if n.endswith(_WEIGHT_SUFFIXES)]
    if skipped_weights or (skipped_private and not files):
        # never persist/advertise a weightless manifest: a peer pull
        # would "succeed" and fail confusingly at restore time — an
        # omitted README is survivable, omitted weights are not
        what = (f"including weights: {', '.join(sorted(skipped_weights)[:5])}"
                if skipped_weights else
                f"and nothing public is cached: "
                f"{', '.join(sorted(skipped_private)[:5])}")
        raise PermissionError(
            f"{len(skipped_private)} cached file(s) for {model} are "
            f"auth-scoped, {what} — rerun with include_private=True / "
            "--include-private to explicitly republish them on the "
            "public peer plane. (Note: a logged-in hf client sends its "
            "token on PUBLIC repos too, marking them auth-scoped here; "
            "if this repo is public, --include-private is safe.)")
    if skipped_private:
        log.warning(
            "manifest for %s omits %d auth-scoped (gated-repo) file(s): "
            "%s — pass include_private=True / --include-private to "
            "republish them on the public peer plane", model,
            len(skipped_private), ", ".join(sorted(skipped_private)[:5]))
    if not files:
        raise FileNotFoundError(
            f"no cached objects match {model}/resolve/ — was the model "
            "pulled through this proxy?")
    record = {
        "name": model, "source": source, "synthesized": True,
        "files": sorted(files.values(), key=lambda f: f["name"]),
    }
    if persist:
        _persist_manifest(store, manifest_key(source, model), record, set())
        log.info("synthesized manifest for %s: %d files from the proxy "
                 "cache", model, len(files))
    return record


def _synthesize_ollama_manifest(store: Store, model: str,
                                persist: bool = True,
                                include_private: bool = False) -> dict:
    """Ollama flavor of :func:`synthesize_manifest`: the proxy cached the
    registry-v2 manifest under its ``/v2/{name}/manifests/{tag}`` URI and
    every layer under its ``blobs/{digest}`` URI — resolve the manifest,
    map layers to their cached blob keys, persist the pull-shaped
    record.

    ``include_private`` is accepted for signature parity with the HF
    flavor but has no effect: registry-v2 bearer tokens are mandatory
    even for public pulls, so auth presence is not a gating signal here
    (token-scoped layers republish with digest verification + warning).
    """
    del include_private  # see docstring
    import json as _json

    from demodel_tpu.registry.ollama import normalize_name
    from demodel_tpu.store import key_for_uri

    name, tag = normalize_name(model)
    suffix = f"/v2/{name}/manifests/{tag}"
    manifest = None
    manifest_uri = None
    for key in store.list():
        meta = store.meta(key) or {}
        uri = meta.get("uri", "")
        if not uri.split("?", 1)[0].endswith(suffix):
            continue
        try:
            manifest = _json.loads(b"".join(store.stream(key)).decode())
            manifest_uri = uri
            break
        except ValueError:
            continue
    if manifest is None:
        raise FileNotFoundError(
            f"no cached registry-v2 manifest matches {suffix} — was "
            "the model pulled through this proxy?")
    base = manifest_uri.split("?", 1)[0][: -len(suffix)]
    # blob URI → cached key. NOTE on auth semantics (reviewer r5): the
    # registry-v2 token dance is protocol-MANDATORY — `ollama pull` of a
    # fully public model still sends `Authorization: Bearer <anonymous
    # token>` on every blob fetch, so auth_scope presence carries NO
    # gating signal here (unlike the HF flavor, where anonymous pulls
    # are the norm and the include_private gate applies). Credentialed
    # copies are republished with digest verification against the
    # manifest — the content-address proof — plus a loud warning; a
    # truly private registry's operator is warned not to synthesize.
    by_uri: dict[str, str] = {}
    for key in store.list():
        meta = store.meta(key) or {}
        uri = (meta.get("uri") or "").split("?", 1)[0]
        if f"/v2/{name}/blobs/" in uri:
            by_uri.setdefault(uri, key)
    files = []
    republished_scoped = 0
    layers = list(manifest.get("layers", []))
    if manifest.get("config"):
        layers.append(manifest["config"])
    for layer in layers:
        digest = layer.get("digest", "")
        sha = digest.split(":", 1)[-1]
        blob_uri = f"{base}/v2/{name}/blobs/{digest}"
        blob_key = key_for_uri(blob_uri)
        if not store.has(blob_key):
            src_key = by_uri.get(blob_uri)
            # a public digest-indexed copy of the same bytes beats a
            # credentialed copy: prefer the zero-copy materialize path
            if store.has_digest(sha):
                src_key = None
            elif src_key is None:
                raise FileNotFoundError(
                    f"layer {digest[:19]} of {model} not in the cache")
            blob_key = key_for_uri(f"demodel://synth/{model}/{sha}")
            if not store.has(blob_key):
                pub_meta = {"uri": blob_uri, "sha256": sha,
                            "synthesized": True}
                if src_key is None:
                    # public bytes already digest-indexed: zero-copy link
                    store.materialize(blob_key, sha, pub_meta)
                else:
                    # credentialed copy: re-hash while copying — the
                    # manifest digest is the integrity proof that these
                    # are exactly the registry's content-addressed bytes
                    if store.is_private(src_key):
                        republished_scoped += 1
                    w = store.begin(blob_key)
                    try:
                        for chunk in store.stream(src_key):
                            w.append(chunk)
                        if w.digest() != sha:
                            w.abort(keep_partial=False)
                            raise IOError(
                                f"cached layer {digest[:19]} does not "
                                "match its manifest digest")
                        w.commit(pub_meta)
                    except BaseException:
                        if w._open:  # noqa: SLF001 — writer state check
                            w.abort(keep_partial=False)
                        raise
        files.append({
            "name": digest.split(":", 1)[-1],
            "key": blob_key,
            "size": int(layer.get("size") or store.size(blob_key)),
            "sha256": digest.split(":", 1)[-1],
            "media_type": layer.get("mediaType", ""),
        })
    if republished_scoped:
        log.warning(
            "ollama manifest for %s republished %d token-scoped layer(s) "
            "on the public peer plane (registry-v2 bearer tokens are "
            "protocol-mandatory, so auth presence does not imply a "
            "private registry — do NOT run `manifest` against models "
            "pulled from a credentials-gated registry)",
            model, republished_scoped)
    record = {"name": model, "source": "ollama", "synthesized": True,
              "files": sorted(files, key=lambda f: f["name"])}
    if persist:
        _persist_manifest(store, manifest_key("ollama", model), record,
                          set())
        log.info("synthesized ollama manifest for %s: %d layers", model,
                 len(files))
    return record


def materialize(report: PullReport | dict, store: Store, dest: Path) -> list[Path]:
    """Write a pulled snapshot out of the store into ``dest`` with original
    filenames — what a foreign tool (``transformers.from_pretrained``)
    expects on disk."""
    if isinstance(report, PullReport):
        files = [(f.name, f.key) for f in report.files]
    else:
        files = [(f["name"], f["key"]) for f in report["files"]]
    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    out = []
    for name, key in files:
        safe = name.replace(":", "_").replace("/", "_")
        path = dest / safe
        with open(path, "wb") as f:
            for chunk in store.stream(key):
                f.write(chunk)
        out.append(path)
    return out
