from demodel_tpu.formats import gguf, safetensors

__all__ = ["gguf", "safetensors"]
