"""GGUF container + quant-block codecs.

Parsing is range-read oriented like :mod:`.safetensors`: the header walk
yields absolute byte ranges per tensor so the HBM sink can stream each
device's rows without loading the file. Block layouts follow the public
llama.cpp/ggml format spec (the unavoidable constants: block sizes, scale
packing); all encode/decode here is an original numpy implementation, with
the on-device dequant kernels in :mod:`demodel_tpu.ops.dequant`.

Container: ``GGUF`` magic, version 3, tensor/kv counts, metadata KVs,
tensor infos (name, dims innermost-first, ggml type, data offset), then the
data section aligned to ``general.alignment`` (default 32).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

MAGIC = b"GGUF"
VERSION = 3
DEFAULT_ALIGNMENT = 32

# ggml tensor types (stable public ABI ids)
GGML_F32 = 0
GGML_F16 = 1
GGML_Q4_0 = 2
GGML_Q8_0 = 8
GGML_Q2_K = 10
GGML_Q3_K = 11
GGML_Q4_K = 12
GGML_Q5_K = 13
GGML_Q6_K = 14

QK = 32       # elements per Q4_0/Q8_0 block
QK_K = 256    # elements per K-quant super-block

Q4_0_BLOCK_BYTES = 2 + QK // 2          # f16 d + 16 nibble bytes = 18
Q8_0_BLOCK_BYTES = 2 + QK               # f16 d + 32 int8        = 34
K_BLOCK_BYTES = {
    GGML_Q2_K: 16 + QK_K // 4 + 2 + 2,              # scales+qs+d+dmin = 84
    GGML_Q3_K: QK_K // 8 + QK_K // 4 + 12 + 2,      # hmask+qs+scales+d = 110
    GGML_Q4_K: 2 + 2 + 12 + QK_K // 2,              # d+dmin+scales+qs = 144
    GGML_Q5_K: 2 + 2 + 12 + QK_K // 8 + QK_K // 2,  # +qh              = 176
    GGML_Q6_K: QK_K // 2 + QK_K // 4 + QK_K // 16 + 2,  # ql+qh+sc+d   = 210
}

_BLOCK_GEOM = {
    GGML_F32: (1, 4),
    GGML_F16: (1, 2),
    GGML_Q4_0: (QK, Q4_0_BLOCK_BYTES),
    GGML_Q8_0: (QK, Q8_0_BLOCK_BYTES),
    **{t: (QK_K, b) for t, b in K_BLOCK_BYTES.items()},
}

# GGUF metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32 = 0, 1, 2, 3, 4, 5
_T_F32, _T_BOOL, _T_STR, _T_ARR, _T_U64, _T_I64, _T_F64 = 6, 7, 8, 9, 10, 11, 12

_SCALAR_FMT = {_T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
               _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_BOOL: "<?",
               _T_U64: "<Q", _T_I64: "<q", _T_F64: "<d"}


@dataclass(frozen=True)
class GGUFTensor:
    name: str
    ggml_type: int
    shape: tuple[int, ...]   # numpy (row-major) order — file stores reversed
    start: int               # absolute offset of first data byte
    nbytes: int


@dataclass(frozen=True)
class GGUFIndex:
    tensors: dict[str, GGUFTensor]
    metadata: dict
    alignment: int
    data_start: int


def tensor_nbytes(ggml_type: int, n_elems: int) -> int:
    blk, bpb = _BLOCK_GEOM[ggml_type]
    if n_elems % blk != 0:
        raise ValueError(f"{n_elems} elements not a multiple of block {blk}")
    return n_elems // blk * bpb


# ------------------------------------------------------------------ reader


class _Cursor:
    """Sequential reader over a range-reader with a sliding buffer."""

    def __init__(self, read_at):
        self.read_at = read_at
        self.pos = 0

    def take(self, n: int) -> bytes:
        b = bytes(self.read_at(self.pos, n))
        if len(b) != n:
            raise ValueError(f"truncated GGUF (wanted {n} at {self.pos})")
        self.pos += n
        return b

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]

    def string(self) -> str:
        n = self.u64()
        if n > (1 << 20):
            raise ValueError(f"GGUF string length {n} out of bounds")
        return self.take(n).decode("utf-8")

    def value(self, t: int):
        if t in _SCALAR_FMT:
            fmt = _SCALAR_FMT[t]
            return struct.unpack(fmt, self.take(struct.calcsize(fmt)))[0]
        if t == _T_STR:
            return self.string()
        if t == _T_ARR:
            et = self.u32()
            n = self.u64()
            if n > (1 << 24):
                raise ValueError(f"GGUF array length {n} out of bounds")
            return [self.value(et) for _ in range(n)]
        raise ValueError(f"unknown GGUF value type {t}")


def read_index_from(read_at) -> GGUFIndex:
    c = _Cursor(read_at)
    if c.take(4) != MAGIC:
        raise ValueError("not a GGUF file (bad magic)")
    version = c.u32()
    if version not in (2, 3):
        raise ValueError(f"unsupported GGUF version {version}")
    n_tensors = c.u64()
    n_kv = c.u64()
    if n_tensors > (1 << 20) or n_kv > (1 << 20):
        raise ValueError("GGUF counts out of bounds")
    metadata = {}
    for _ in range(n_kv):
        key = c.string()
        t = c.u32()
        metadata[key] = c.value(t)
    alignment = int(metadata.get("general.alignment", DEFAULT_ALIGNMENT))
    infos = []
    for _ in range(n_tensors):
        name = c.string()
        n_dims = c.u32()
        if n_dims > 8:
            raise ValueError(f"{name}: {n_dims} dims out of bounds")
        dims = [c.u64() for _ in range(n_dims)]
        ggml_type = c.u32()
        offset = c.u64()
        if ggml_type not in _BLOCK_GEOM:
            raise ValueError(f"{name}: unsupported ggml type {ggml_type}")
        # file order is innermost-first; numpy shape is the reverse
        shape = tuple(reversed([int(d) for d in dims])) if dims else ()
        infos.append((name, ggml_type, shape, offset))
    data_start = (c.pos + alignment - 1) // alignment * alignment
    tensors = {}
    for name, ggml_type, shape, offset in infos:
        n_elems = int(np.prod(shape, dtype=np.int64)) if shape else 1
        tensors[name] = GGUFTensor(
            name=name, ggml_type=ggml_type, shape=shape,
            start=data_start + offset,
            nbytes=tensor_nbytes(ggml_type, n_elems),
        )
    return GGUFIndex(tensors=tensors, metadata=metadata, alignment=alignment,
                     data_start=data_start)


def parse(blob: bytes) -> GGUFIndex:
    mv = memoryview(blob)
    return read_index_from(lambda off, ln: mv[off:off + ln])


# ------------------------------------------------------------------ writer


def _w_string(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack("<Q", len(b)) + b


def serialize(tensors: dict[str, np.ndarray],
              types: dict[str, int] | int = GGML_F32,
              metadata: dict | None = None,
              alignment: int = DEFAULT_ALIGNMENT) -> bytes:
    """Write a GGUF blob, quantizing each tensor to its requested type."""
    out = bytearray()
    meta = dict(metadata or {})
    meta.setdefault("general.alignment", alignment)
    out += MAGIC
    out += struct.pack("<IQQ", VERSION, len(tensors), len(meta))
    for k, v in meta.items():
        out += _w_string(k)
        if isinstance(v, bool):
            out += struct.pack("<I", _T_BOOL) + struct.pack("<?", v)
        elif isinstance(v, int):
            out += struct.pack("<I", _T_U32) + struct.pack("<I", v)
        elif isinstance(v, float):
            out += struct.pack("<I", _T_F32) + struct.pack("<f", v)
        elif isinstance(v, str):
            out += struct.pack("<I", _T_STR) + _w_string(v)
        else:
            raise ValueError(f"unsupported metadata value for {k}: {v!r}")
    bodies = []
    offset = 0
    for name, arr in tensors.items():
        t = types if isinstance(types, int) else types.get(name, GGML_F32)
        raw = encode(np.asarray(arr, dtype=np.float32), t)
        out += _w_string(name)
        dims = list(reversed(arr.shape))
        out += struct.pack("<I", len(dims))
        for d in dims:
            out += struct.pack("<Q", d)
        out += struct.pack("<IQ", t, offset)
        bodies.append(raw)
        offset += len(raw)
        pad = (-offset) % alignment
        bodies.append(b"\0" * pad)
        offset += pad
    pad = (-len(out)) % alignment
    out += b"\0" * pad
    for b in bodies:
        out += b
    return bytes(out)


# ------------------------------------------------------ block encode/decode
#
# Encoders here exist for fixtures and round-trip tests: they produce VALID
# blocks with sane (absmax / absmax-min) scale choices, not llama.cpp's
# search-optimized ones. Decoders are the normative spec implementation the
# pallas kernels are tested against.


def encode(arr: np.ndarray, ggml_type: int) -> bytes:
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
    if ggml_type == GGML_F32:
        return flat.tobytes()
    if ggml_type == GGML_F16:
        return flat.astype(np.float16).tobytes()
    blk, _ = _BLOCK_GEOM[ggml_type]
    if flat.size % blk != 0:
        raise ValueError(f"{flat.size} elements not a multiple of {blk}")
    x = flat.reshape(-1, blk)
    if ggml_type == GGML_Q8_0:
        return _enc_q8_0(x)
    if ggml_type == GGML_Q4_0:
        return _enc_q4_0(x)
    if ggml_type == GGML_Q2_K:
        return _enc_q2_k(x)
    if ggml_type == GGML_Q3_K:
        return _enc_q3_k(x)
    if ggml_type == GGML_Q4_K:
        return _enc_q4_k(x)
    if ggml_type == GGML_Q5_K:
        return _enc_q5_k(x)
    if ggml_type == GGML_Q6_K:
        return _enc_q6_k(x)
    raise ValueError(f"unsupported ggml type {ggml_type}")


def decode_raw(t: GGUFTensor, raw: bytes):
    """Split packed blocks into typed column arrays ("parts").

    F32/F16 → the numpy array itself. Quant types → a tuple of arrays
    (scales first) that :mod:`demodel_tpu.ops.dequant` consumes on device —
    the host→device link carries only the quantized payload.
    """
    if t.ggml_type == GGML_F32:
        return np.frombuffer(raw, np.float32).reshape(t.shape)
    if t.ggml_type == GGML_F16:
        return np.frombuffer(raw, np.float16).reshape(t.shape)
    blk, bpb = _BLOCK_GEOM[t.ggml_type]
    b = np.frombuffer(raw, np.uint8).reshape(-1, bpb)
    if t.ggml_type == GGML_Q8_0:
        d = b[:, 0:2].copy().view(np.float16).reshape(-1)
        qs = b[:, 2:].view(np.int8)
        return d, qs
    if t.ggml_type == GGML_Q4_0:
        d = b[:, 0:2].copy().view(np.float16).reshape(-1)
        qs = b[:, 2:]
        return d, qs
    if t.ggml_type == GGML_Q2_K:
        scales = b[:, 0:16]
        qs = b[:, 16:80]
        d = b[:, 80:82].copy().view(np.float16).reshape(-1)
        dmin = b[:, 82:84].copy().view(np.float16).reshape(-1)
        return d, dmin, scales, qs
    if t.ggml_type == GGML_Q3_K:
        hmask = b[:, 0:32]
        qs = b[:, 32:96]
        scales = b[:, 96:108]
        d = b[:, 108:110].copy().view(np.float16).reshape(-1)
        return d, scales, hmask, qs
    if t.ggml_type == GGML_Q4_K:
        d = b[:, 0:2].copy().view(np.float16).reshape(-1)
        dmin = b[:, 2:4].copy().view(np.float16).reshape(-1)
        scales = b[:, 4:16]
        qs = b[:, 16:144]
        return d, dmin, scales, qs
    if t.ggml_type == GGML_Q5_K:
        d = b[:, 0:2].copy().view(np.float16).reshape(-1)
        dmin = b[:, 2:4].copy().view(np.float16).reshape(-1)
        scales = b[:, 4:16]
        qh = b[:, 16:48]
        qs = b[:, 48:176]
        return d, dmin, scales, qh, qs
    if t.ggml_type == GGML_Q6_K:
        ql = b[:, 0:128]
        qh = b[:, 128:192]
        sc = b[:, 192:208].view(np.int8)
        d = b[:, 208:210].copy().view(np.float16).reshape(-1)
        return d, sc, ql, qh
    raise ValueError(f"unsupported ggml type {t.ggml_type}")


# -- Q8_0 / Q4_0 ----------------------------------------------------------


def _enc_q8_0(x: np.ndarray) -> bytes:
    amax = np.abs(x).max(axis=1)
    d = (amax / 127.0).astype(np.float16)
    ds = d.astype(np.float32)
    ds[ds == 0] = 1.0
    q = np.clip(np.rint(x / ds[:, None]), -127, 127).astype(np.int8)
    out = np.empty((x.shape[0], Q8_0_BLOCK_BYTES), np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = q.view(np.uint8)
    return out.tobytes()


def _enc_q4_0(x: np.ndarray) -> bytes:
    amax_idx = np.abs(x).argmax(axis=1)
    maxv = x[np.arange(x.shape[0]), amax_idx]
    d = (maxv / -8.0).astype(np.float16)
    ds = d.astype(np.float32)
    ds[ds == 0] = 1.0
    q = np.clip(np.rint(x / ds[:, None]) + 8, 0, 15).astype(np.uint8)
    lo, hi = q[:, :QK // 2], q[:, QK // 2:]
    out = np.empty((x.shape[0], Q4_0_BLOCK_BYTES), np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = lo | (hi << 4)
    return out.tobytes()


def ref_dequant_q8_0(d: np.ndarray, qs: np.ndarray) -> np.ndarray:
    return (d.astype(np.float32)[:, None] * qs.astype(np.float32)).reshape(-1)


def ref_dequant_q4_0(d: np.ndarray, qs: np.ndarray) -> np.ndarray:
    lo = (qs & 0xF).astype(np.int16) - 8
    hi = (qs >> 4).astype(np.int16) - 8
    q = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return (d.astype(np.float32)[:, None] * q).reshape(-1)


# -- Q2_K ------------------------------------------------------------------


def _enc_q2_k(x: np.ndarray) -> bytes:
    nb = x.shape[0]
    sub = x.reshape(nb, 16, 16)  # 16 sub-blocks of 16 (logical order)
    mins = np.maximum(0.0, -sub.min(axis=2))
    maxs = sub.max(axis=2) + mins
    d = (maxs.max(axis=1) / (3 * 15)).astype(np.float16)  # scale of scales
    dmin = (mins.max(axis=1) / 15).astype(np.float16)
    ds = d.astype(np.float32)
    ds[ds == 0] = 1.0
    dm = dmin.astype(np.float32)
    dm[dm == 0] = 1.0
    m4 = np.clip(np.rint(mins / dm[:, None]), 0, 15).astype(np.uint8)
    sc_eff = maxs / 3.0
    s4 = np.clip(np.rint(sc_eff / ds[:, None]), 0, 15).astype(np.uint8)
    scales = (s4 | (m4 << 4))
    # quantize against the encoded (decoded-back) scale/min
    dl = ds[:, None] * s4
    ml = dm[:, None] * m4
    dl[dl == 0] = 1.0
    q = np.clip(np.rint((sub + ml[:, :, None]) / dl[:, :, None]), 0, 3)
    q = q.astype(np.uint8)
    # pack: halves of 128; within a half, shift j covers elements 32j..32j+31
    qs = np.zeros((nb, 64), np.uint8)
    for half in range(2):
        for j in range(4):
            seg = q.reshape(nb, 256)[:, half * 128 + 32 * j:
                                     half * 128 + 32 * (j + 1)]
            qs[:, half * 32:half * 32 + 32] |= seg << (2 * j)
    out = np.empty((nb, K_BLOCK_BYTES[GGML_Q2_K]), np.uint8)
    out[:, 0:16] = scales
    out[:, 16:80] = qs
    out[:, 80:82] = d.view(np.uint8).reshape(-1, 2)
    out[:, 82:84] = dmin.view(np.uint8).reshape(-1, 2)
    return out.tobytes()


def ref_dequant_q2_k(d, dmin, scales, qs) -> np.ndarray:
    nb = d.shape[0]
    y = np.empty((nb, 256), np.float32)
    df = d.astype(np.float32)
    mf = dmin.astype(np.float32)
    for half in range(2):
        q = qs[:, half * 32:(half + 1) * 32]
        for j in range(4):
            grp = ((q >> (2 * j)) & 3).astype(np.float32)  # (nb, 32)
            for sub in range(2):
                is_ = half * 8 + 2 * j + sub
                sc = scales[:, is_]
                dl = df * (sc & 0xF)
                ml = mf * (sc >> 4)
                seg = grp[:, sub * 16:(sub + 1) * 16]
                y[:, half * 128 + 32 * j + 16 * sub:
                  half * 128 + 32 * j + 16 * (sub + 1)] = \
                    dl[:, None] * seg - ml[:, None]
    return y.reshape(-1)


# -- Q3_K ------------------------------------------------------------------


def unpack_q3k_scales(scales: np.ndarray) -> np.ndarray:
    """12 packed bytes → 16 signed 6-bit scales (already -32), via the
    spec's three-dword shuffle."""
    aux = np.empty((scales.shape[0], 4), np.uint32)
    raw = scales.copy().view("<u4")  # (nb, 3)
    tmp = raw[:, 2]
    kmask1, kmask2 = 0x03030303, 0x0F0F0F0F
    aux[:, 0] = (raw[:, 0] & kmask2) | (((tmp >> 0) & kmask1) << 4)
    aux[:, 1] = (raw[:, 1] & kmask2) | (((tmp >> 2) & kmask1) << 4)
    aux[:, 2] = ((raw[:, 0] >> 4) & kmask2) | (((tmp >> 4) & kmask1) << 4)
    aux[:, 3] = ((raw[:, 1] >> 4) & kmask2) | (((tmp >> 6) & kmask1) << 4)
    sc = aux.view(np.int8).reshape(scales.shape[0], 16).astype(np.int32) - 32
    return sc


def _enc_q3_k(x: np.ndarray) -> bytes:
    nb = x.shape[0]
    sub = x.reshape(nb, 16, 16)
    amax = np.abs(sub).max(axis=2)
    d = (amax.max(axis=1) / (4 * 31)).astype(np.float16)
    ds = d.astype(np.float32)
    ds[ds == 0] = 1.0
    sc6 = np.clip(np.rint((amax / 4.0) / ds[:, None]), -32, 31).astype(np.int32)
    dl = ds[:, None] * sc6
    dl[dl == 0] = 1.0
    q = np.clip(np.rint(sub / dl[:, :, None]), -4, 3).astype(np.int32) + 4
    q = q.reshape(nb, 256).astype(np.uint8)  # 0..7: low 2 bits + high bit
    low = (q & 3)
    high = (q >> 2) & 1
    qs = np.zeros((nb, 64), np.uint8)
    hmask = np.zeros((nb, 32), np.uint8)
    for half in range(2):
        for j in range(4):
            seg = low[:, half * 128 + 32 * j: half * 128 + 32 * (j + 1)]
            qs[:, half * 32:half * 32 + 32] |= seg << (2 * j)
    for grp in range(8):
        hmask |= high[:, 32 * grp:32 * (grp + 1)] << grp
    # pack 16 6-bit scales (+32 offset) into 12 bytes: the inverse of
    # unpack_q3k_scales' three-dword shuffle
    v = (sc6 + 32).astype(np.uint32)  # (nb, 16), values 0..63

    def low_nibbles(cols):
        b = np.zeros(nb, np.uint32)
        for i, c in enumerate(cols):
            b |= (v[:, c] & 0xF) << (8 * i)
        return b

    raw0 = low_nibbles([0, 1, 2, 3]) | (low_nibbles([8, 9, 10, 11]) << 4)
    raw1 = low_nibbles([4, 5, 6, 7]) | (low_nibbles([12, 13, 14, 15]) << 4)
    raw2 = np.zeros(nb, np.uint32)
    for i in range(4):
        raw2 |= ((v[:, 0 + i] >> 4) & 3) << (8 * i + 0)
        raw2 |= ((v[:, 4 + i] >> 4) & 3) << (8 * i + 2)
        raw2 |= ((v[:, 8 + i] >> 4) & 3) << (8 * i + 4)
        raw2 |= ((v[:, 12 + i] >> 4) & 3) << (8 * i + 6)
    scales = np.stack([raw0, raw1, raw2], axis=1).astype("<u4").view(np.uint8)
    out = np.empty((nb, K_BLOCK_BYTES[GGML_Q3_K]), np.uint8)
    out[:, 0:32] = hmask
    out[:, 32:96] = qs
    out[:, 96:108] = scales.reshape(nb, 12)
    out[:, 108:110] = d.view(np.uint8).reshape(-1, 2)
    return out.tobytes()


def ref_dequant_q3_k(d, scales, hmask, qs) -> np.ndarray:
    nb = d.shape[0]
    sc = unpack_q3k_scales(scales)  # (nb,16) int32, -32 applied
    df = d.astype(np.float32)
    y = np.empty((nb, 256), np.float32)
    for half in range(2):
        q = qs[:, half * 32:(half + 1) * 32]
        for j in range(4):
            grp_i = half * 4 + j
            low = ((q >> (2 * j)) & 3).astype(np.int32)
            hbit = ((hmask >> grp_i) & 1).astype(np.int32)
            qv = low - np.where(hbit != 0, 0, 4)
            for sub in range(2):
                is_ = half * 8 + 2 * j + sub
                dl = df * sc[:, is_]
                seg = qv[:, sub * 16:(sub + 1) * 16].astype(np.float32)
                y[:, half * 128 + 32 * j + 16 * sub:
                  half * 128 + 32 * j + 16 * (sub + 1)] = dl[:, None] * seg
    return y.reshape(-1)


# -- Q4_K / Q5_K ------------------------------------------------------------


def unpack_k4_scales(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """12 packed bytes → (sc, m): 8 six-bit scales + 8 six-bit mins."""
    q = scales.astype(np.uint16)
    sc = np.empty((scales.shape[0], 8), np.uint16)
    m = np.empty((scales.shape[0], 8), np.uint16)
    for j in range(8):
        if j < 4:
            sc[:, j] = q[:, j] & 63
            m[:, j] = q[:, j + 4] & 63
        else:
            sc[:, j] = (q[:, j + 4] & 0xF) | ((q[:, j - 4] >> 6) << 4)
            m[:, j] = (q[:, j + 4] >> 4) | ((q[:, j] >> 6) << 4)
    return sc, m


def _pack_k4_scales(sc: np.ndarray, m: np.ndarray) -> np.ndarray:
    nb = sc.shape[0]
    out = np.zeros((nb, 12), np.uint8)
    for j in range(4):
        out[:, j] = (sc[:, j] & 63) | ((sc[:, j + 4] >> 4) << 6)
        out[:, j + 4] = (m[:, j] & 63) | ((m[:, j + 4] >> 4) << 6)
        out[:, j + 8] = (sc[:, j + 4] & 0xF) | ((m[:, j + 4] & 0xF) << 4)
    return out


def _kq_scale_min(x_sub: np.ndarray, qmax: int):
    """Per-sub-block (scale, min) for absmax-style K-quant encoding."""
    mins = np.maximum(0.0, -x_sub.min(axis=2))
    maxs = x_sub.max(axis=2) + mins
    d = (maxs.max(axis=1) / (63 * qmax)).astype(np.float16)
    dmin = (mins.max(axis=1) / 63).astype(np.float16)
    ds = d.astype(np.float32)
    ds[ds == 0] = 1.0
    dm = dmin.astype(np.float32)
    dm[dm == 0] = 1.0
    sc = np.clip(np.rint((maxs / qmax) / ds[:, None]), 0, 63).astype(np.uint16)
    mn = np.clip(np.rint(mins / dm[:, None]), 0, 63).astype(np.uint16)
    return d, dmin, sc, mn


def _enc_q4_k(x: np.ndarray) -> bytes:
    nb = x.shape[0]
    sub = x.reshape(nb, 8, 32)
    d, dmin, sc, mn = _kq_scale_min(sub, 15)
    ds = d.astype(np.float32)
    dm = dmin.astype(np.float32)
    dl = ds[:, None] * sc
    ml = dm[:, None] * mn
    dl[dl == 0] = 1.0
    q = np.clip(np.rint((sub + ml[:, :, None]) / dl[:, :, None]), 0, 15)
    q = q.astype(np.uint8).reshape(nb, 256)
    qs = np.zeros((nb, 128), np.uint8)
    for j in range(4):
        lo = q[:, 64 * j:64 * j + 32]
        hi = q[:, 64 * j + 32:64 * (j + 1)]
        qs[:, 32 * j:32 * (j + 1)] = lo | (hi << 4)
    out = np.empty((nb, K_BLOCK_BYTES[GGML_Q4_K]), np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:4] = dmin.view(np.uint8).reshape(-1, 2)
    out[:, 4:16] = _pack_k4_scales(sc, mn)
    out[:, 16:] = qs
    return out.tobytes()


def ref_dequant_q4_k(d, dmin, scales, qs) -> np.ndarray:
    nb = d.shape[0]
    sc, mn = unpack_k4_scales(scales)
    df = d.astype(np.float32)
    mf = dmin.astype(np.float32)
    y = np.empty((nb, 256), np.float32)
    for j in range(4):
        q = qs[:, 32 * j:32 * (j + 1)]
        d1 = df * sc[:, 2 * j]
        m1 = mf * mn[:, 2 * j]
        d2 = df * sc[:, 2 * j + 1]
        m2 = mf * mn[:, 2 * j + 1]
        y[:, 64 * j:64 * j + 32] = d1[:, None] * (q & 0xF) - m1[:, None]
        y[:, 64 * j + 32:64 * (j + 1)] = d2[:, None] * (q >> 4) - m2[:, None]
    return y.reshape(-1)


def _enc_q5_k(x: np.ndarray) -> bytes:
    nb = x.shape[0]
    sub = x.reshape(nb, 8, 32)
    d, dmin, sc, mn = _kq_scale_min(sub, 31)
    ds = d.astype(np.float32)
    dm = dmin.astype(np.float32)
    dl = ds[:, None] * sc
    ml = dm[:, None] * mn
    dl[dl == 0] = 1.0
    q = np.clip(np.rint((sub + ml[:, :, None]) / dl[:, :, None]), 0, 31)
    q = q.astype(np.uint8).reshape(nb, 256)
    qs = np.zeros((nb, 128), np.uint8)
    qh = np.zeros((nb, 32), np.uint8)
    for j in range(4):
        q1 = q[:, 64 * j:64 * j + 32]
        q2 = q[:, 64 * j + 32:64 * (j + 1)]
        qs[:, 32 * j:32 * (j + 1)] = (q1 & 0xF) | ((q2 & 0xF) << 4)
        qh |= (q1 >> 4) << (2 * j)
        qh |= (q2 >> 4) << (2 * j + 1)
    out = np.empty((nb, K_BLOCK_BYTES[GGML_Q5_K]), np.uint8)
    out[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    out[:, 2:4] = dmin.view(np.uint8).reshape(-1, 2)
    out[:, 4:16] = _pack_k4_scales(sc, mn)
    out[:, 16:48] = qh
    out[:, 48:] = qs
    return out.tobytes()


def ref_dequant_q5_k(d, dmin, scales, qh, qs) -> np.ndarray:
    nb = d.shape[0]
    sc, mn = unpack_k4_scales(scales)
    df = d.astype(np.float32)
    mf = dmin.astype(np.float32)
    y = np.empty((nb, 256), np.float32)
    for j in range(4):
        q = qs[:, 32 * j:32 * (j + 1)]
        h1 = ((qh >> (2 * j)) & 1).astype(np.uint8)
        h2 = ((qh >> (2 * j + 1)) & 1).astype(np.uint8)
        q1 = (q & 0xF) + (h1 << 4)
        q2 = (q >> 4) + (h2 << 4)
        d1 = df * sc[:, 2 * j]
        m1 = mf * mn[:, 2 * j]
        d2 = df * sc[:, 2 * j + 1]
        m2 = mf * mn[:, 2 * j + 1]
        y[:, 64 * j:64 * j + 32] = d1[:, None] * q1 - m1[:, None]
        y[:, 64 * j + 32:64 * (j + 1)] = d2[:, None] * q2 - m2[:, None]
    return y.reshape(-1)


# -- Q6_K ------------------------------------------------------------------


def _enc_q6_k(x: np.ndarray) -> bytes:
    nb = x.shape[0]
    sub = x.reshape(nb, 16, 16)
    amax = np.abs(sub).max(axis=2)
    d = (amax.max(axis=1) / (32 * 127)).astype(np.float16)
    ds = d.astype(np.float32)
    ds[ds == 0] = 1.0
    sc = np.clip(np.rint((amax / 32.0) / ds[:, None]), -128, 127).astype(np.int8)
    dl = ds[:, None] * sc.astype(np.float32)
    dl[dl == 0] = 1.0
    q = np.clip(np.rint(sub / dl[:, :, None]), -32, 31).astype(np.int32) + 32
    q = q.reshape(nb, 256).astype(np.uint8)  # 6-bit values
    ql = np.zeros((nb, 128), np.uint8)
    qh = np.zeros((nb, 64), np.uint8)
    for half in range(2):
        base = half * 128
        q1 = q[:, base + 0:base + 32]
        q2 = q[:, base + 32:base + 64]
        q3 = q[:, base + 64:base + 96]
        q4 = q[:, base + 96:base + 128]
        ql[:, half * 64 + 0:half * 64 + 32] = (q1 & 0xF) | ((q3 & 0xF) << 4)
        ql[:, half * 64 + 32:half * 64 + 64] = (q2 & 0xF) | ((q4 & 0xF) << 4)
        qh[:, half * 32:half * 32 + 32] = (
            (q1 >> 4) | ((q2 >> 4) << 2) | ((q3 >> 4) << 4) | ((q4 >> 4) << 6))
    out = np.empty((nb, K_BLOCK_BYTES[GGML_Q6_K]), np.uint8)
    out[:, 0:128] = ql
    out[:, 128:192] = qh
    out[:, 192:208] = sc.view(np.uint8)
    out[:, 208:210] = d.view(np.uint8).reshape(-1, 2)
    return out.tobytes()


def ref_dequant_q6_k(d, sc, ql, qh) -> np.ndarray:
    nb = d.shape[0]
    df = d.astype(np.float32)
    scf = sc.astype(np.float32)
    y = np.empty((nb, 256), np.float32)
    for half in range(2):
        base = half * 128
        l = ql[:, half * 64:half * 64 + 32]
        l2 = ql[:, half * 64 + 32:half * 64 + 64]
        h = qh[:, half * 32:half * 32 + 32]
        q1 = ((l & 0xF) | (((h >> 0) & 3) << 4)).astype(np.int32) - 32
        q2 = ((l2 & 0xF) | (((h >> 2) & 3) << 4)).astype(np.int32) - 32
        q3 = ((l >> 4) | (((h >> 4) & 3) << 4)).astype(np.int32) - 32
        q4 = ((l2 >> 4) | (((h >> 6) & 3) << 4)).astype(np.int32) - 32
        for qv, col in ((q1, 0), (q2, 32), (q3, 64), (q4, 96)):
            for subi in range(2):
                is_ = half * 8 + col // 16 + subi
                seg = qv[:, subi * 16:(subi + 1) * 16].astype(np.float32)
                y[:, base + col + 16 * subi:base + col + 16 * (subi + 1)] = \
                    (df * scf[:, is_])[:, None] * seg
    return y.reshape(-1)


#: numpy reference decoders by type (normative for the pallas kernels)
REF_DEQUANT = {
    GGML_Q8_0: ref_dequant_q8_0,
    GGML_Q4_0: ref_dequant_q4_0,
    GGML_Q2_K: ref_dequant_q2_k,
    GGML_Q3_K: ref_dequant_q3_k,
    GGML_Q4_K: ref_dequant_q4_k,
    GGML_Q5_K: ref_dequant_q5_k,
    GGML_Q6_K: ref_dequant_q6_k,
}
