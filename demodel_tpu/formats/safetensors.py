"""safetensors parsing/serialization, range-read oriented.

The HBM sink never loads whole checkpoint files: it reads the 8-byte length
prefix + JSON header, then issues per-tensor (per-shard) byte-range reads.
This module owns the header math; it is wire-compatible with the upstream
``safetensors`` wheel (parity-tested in tests/test_formats.py).

Format: ``u64le header_len | header JSON | data``; each tensor entry is
``{"dtype": TAG, "shape": [...], "data_offsets": [start, end]}`` with
offsets relative to the data section.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass

import numpy as np

try:  # bf16 & friends — present in this environment (jax dependency)
    import ml_dtypes
except ImportError:  # pragma: no cover
    ml_dtypes = None

#: safetensors dtype tag → numpy dtype
_DTYPES: dict[str, np.dtype] = {
    "F64": np.dtype(np.float64),
    "F32": np.dtype(np.float32),
    "F16": np.dtype(np.float16),
    "I64": np.dtype(np.int64),
    "I32": np.dtype(np.int32),
    "I16": np.dtype(np.int16),
    "I8": np.dtype(np.int8),
    "U8": np.dtype(np.uint8),
    "BOOL": np.dtype(np.bool_),
    "U16": np.dtype(np.uint16),
    "U32": np.dtype(np.uint32),
    "U64": np.dtype(np.uint64),
}
if ml_dtypes is not None:
    _DTYPES["BF16"] = np.dtype(ml_dtypes.bfloat16)
    _DTYPES["F8_E4M3"] = np.dtype(ml_dtypes.float8_e4m3fn)
    _DTYPES["F8_E5M2"] = np.dtype(ml_dtypes.float8_e5m2)

_TAGS = {v: k for k, v in _DTYPES.items()}

MAX_HEADER = 100 << 20  # defensive: a 100MB header is not a checkpoint


def _np_dtype(tag: str) -> np.dtype:
    try:
        return _DTYPES[tag]
    except KeyError:
        raise ValueError(f"unsupported safetensors dtype {tag!r}") from None


def _tag_for(dtype: np.dtype) -> str:
    try:
        return _TAGS[np.dtype(dtype)]
    except KeyError:
        raise ValueError(f"unsupported numpy dtype {dtype!r}") from None


@dataclass(frozen=True)
class TensorSpec:
    name: str
    dtype: str                 # safetensors tag
    shape: tuple[int, ...]
    start: int                 # ABSOLUTE offset of first data byte
    end: int                   # absolute end (exclusive)

    @property
    def nbytes(self) -> int:
        return self.end - self.start

    def to_numpy(self, raw: bytes | memoryview) -> np.ndarray:
        dt = _np_dtype(self.dtype)
        if len(raw) != self.nbytes:
            raise ValueError(
                f"{self.name}: got {len(raw)} bytes, want {self.nbytes}")
        return np.frombuffer(raw, dtype=dt).reshape(self.shape)


@dataclass(frozen=True)
class Index:
    tensors: dict[str, TensorSpec]
    metadata: dict
    data_start: int            # absolute offset where the data section begins
    total_size: int | None     # file size when known (validation)


def _parse_header_json(hdr: bytes, data_start: int,
                       total_size: int | None) -> Index:
    try:
        obj = json.loads(hdr.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ValueError(f"safetensors header is not valid JSON: {e}") from None
    if not isinstance(obj, dict):
        raise ValueError("safetensors header must be a JSON object")
    metadata = obj.pop("__metadata__", {}) or {}
    tensors: dict[str, TensorSpec] = {}
    data_len = None if total_size is None else total_size - data_start
    for name, info in obj.items():
        if not isinstance(info, dict):
            raise ValueError(f"{name}: bad tensor entry")
        try:
            tag = info["dtype"]
            shape = tuple(int(d) for d in info["shape"])
            s, e = info["data_offsets"]
        except (KeyError, TypeError, ValueError):
            raise ValueError(f"{name}: malformed tensor entry") from None
        dt = _np_dtype(tag)
        want = int(np.prod(shape, dtype=np.int64)) * dt.itemsize if shape \
            else dt.itemsize
        if e - s != want:
            raise ValueError(
                f"{name}: data_offsets span {e - s} != dtype×shape {want}")
        if s < 0 or e < s or (data_len is not None and e > data_len):
            raise ValueError(f"{name}: data_offsets [{s},{e}) out of bounds")
        tensors[name] = TensorSpec(name=name, dtype=tag, shape=shape,
                                   start=data_start + s, end=data_start + e)
    return Index(tensors=tensors, metadata=metadata, data_start=data_start,
                 total_size=total_size)


def parse_header(blob: bytes | memoryview) -> Index:
    """Parse the header of an in-memory safetensors file."""
    if len(blob) < 8:
        raise ValueError("truncated safetensors file (no length prefix)")
    (n,) = struct.unpack("<Q", bytes(blob[:8]))
    if n > MAX_HEADER or 8 + n > len(blob):
        raise ValueError(f"safetensors header length {n} out of bounds")
    return _parse_header_json(bytes(blob[8:8 + n]), 8 + n, len(blob))


def read_index_from(read_at, total_size: int | None = None) -> Index:
    """Parse a header through a range-reader ``read_at(offset, length)`` —
    the store/HTTP path, no whole-file load."""
    prefix = bytes(read_at(0, 8))
    if len(prefix) < 8:
        raise ValueError("truncated safetensors file (no length prefix)")
    (n,) = struct.unpack("<Q", prefix)
    if n > MAX_HEADER or (total_size is not None and 8 + n > total_size):
        raise ValueError(f"safetensors header length {n} out of bounds")
    hdr = bytes(read_at(8, n))
    if len(hdr) != n:
        raise ValueError("truncated safetensors header")
    return _parse_header_json(hdr, 8 + n, total_size)


def serialize(tensors: dict[str, np.ndarray],
              metadata: dict | None = None) -> bytes:
    """Write a safetensors blob (sorted offsets, upstream-compatible)."""
    header: dict = {}
    if metadata:
        header["__metadata__"] = {str(k): str(v) for k, v in metadata.items()}
    bodies: list[bytes] = []
    off = 0
    for name, arr in tensors.items():
        arr = np.asarray(arr)
        if arr.ndim and not arr.flags["C_CONTIGUOUS"]:
            # NOT ascontiguousarray unconditionally: it promotes 0-d to (1,)
            arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        header[name] = {
            "dtype": _tag_for(arr.dtype),
            "shape": list(arr.shape),
            "data_offsets": [off, off + len(raw)],
        }
        bodies.append(raw)
        off += len(raw)
    hdr = json.dumps(header, separators=(",", ":")).encode()
    # upstream pads the header with spaces to 8-byte alignment
    pad = (8 - (len(hdr) % 8)) % 8
    hdr += b" " * pad
    return struct.pack("<Q", len(hdr)) + hdr + b"".join(bodies)
