from demodel_tpu.models import bert, gpt2, llama, moe
from demodel_tpu.models.auto import model_from_pull

__all__ = ["bert", "gpt2", "llama", "moe", "model_from_pull"]
