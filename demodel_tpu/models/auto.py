"""Auto dispatch: pulled checkpoint → (forward_fn, params, config).

Closes the delivery loop: ``pull_to_hbm`` lands sharded tensors, this maps
them onto a model family by the pulled ``config.json``'s ``model_type`` and
returns a ready forward function — a pulled model is runnable in one call.
Unknown architectures and config features this stack does not implement
(e.g. rope scaling) are rejected loudly rather than silently mis-executed.
"""

from __future__ import annotations

import functools
import json

from demodel_tpu.models import bert as bert_mod
from demodel_tpu.models import gpt2 as gpt2_mod
from demodel_tpu.models import llama as llama_mod
from demodel_tpu.models.hf_loader import (
    load_bert_params,
    load_gpt2_params,
    load_llama_params,
)
from demodel_tpu.utils.logging import get_logger

log = get_logger("models.auto")

#: config fields whose presence (non-null/non-default) changes numerics in
#: ways this stack does not implement — refuse rather than drift
_UNSUPPORTED = ("rope_scaling", "sliding_window", "attention_bias")


def _check_supported(config: dict) -> None:
    for fld in _UNSUPPORTED:
        v = config.get(fld)
        if v not in (None, False):
            raise ValueError(
                f"config field {fld}={v!r} is not supported by this stack")


def model_from_pull(store, report, mesh=None, placement=None):
    """(forward_fn, params, cfg) from a pulled snapshot.

    ``placement`` (a delivered :class:`~demodel_tpu.sink.hbm.Placement`)
    supplies the weight arrays when given; otherwise weights are delivered
    from the store now under the default plan.
    """
    files = report["files"] if isinstance(report, dict) else [
        vars(f) for f in report.files]
    cfg_file = next((f for f in files if f["name"] == "config.json"), None)
    if cfg_file is None:
        raise ValueError("pulled snapshot has no config.json")
    config = json.loads(bytes(store.get(cfg_file["key"])).decode())
    model_type = config.get("model_type")

    if placement is None:
        from demodel_tpu.sink.hbm import deliver_report_to_hbm

        placement = deliver_report_to_hbm(store, report, mesh=mesh)
    weights = placement.arrays

    if model_type == "llama":
        _check_supported(config)
        cfg = llama_mod.LlamaConfig.from_hf(config)
        params = load_llama_params(weights, cfg)
        fn = functools.partial(llama_mod.forward, cfg=cfg, mesh=mesh)
    elif model_type == "gpt2":
        _check_supported(config)
        cfg = gpt2_mod.GPT2Config.from_hf(config)
        params = load_gpt2_params(weights, cfg)
        fn = functools.partial(gpt2_mod.forward, cfg=cfg, mesh=mesh)
    elif model_type == "bert":
        _check_supported(config)
        cfg = bert_mod.BertConfig.from_hf(config)
        params = load_bert_params(weights, cfg)
        fn = functools.partial(bert_mod.encode, cfg=cfg, mesh=mesh)
    else:
        raise ValueError(f"unsupported model_type {model_type!r} "
                         "(supported: llama, gpt2, bert)")
    log.info("auto: built %s from pulled snapshot (%d tensors)",
             model_type, len(weights))
    return fn, params, cfg
