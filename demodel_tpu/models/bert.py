"""BERT encoder family (the client matrix's ``bert-base-uncased`` config —
reference BASELINE config 3 pulls it via ``transformers``).

Post-LN encoder with additive padding masks; parity with HF
``BertModel``'s last_hidden_state is tested in tests/test_hf_models.py,
including fully-padded rows (which must stay finite — the mask adds a
large negative, never -inf, so softmax keeps a valid distribution)."""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from demodel_tpu.models.common import layer_norm, use_flash_attention as _use_flash


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: str = "float32"

    @classmethod
    def tiny(cls) -> "BertConfig":
        return cls(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=64)

    @classmethod
    def from_hf(cls, config: dict) -> "BertConfig":
        return cls(
            vocab_size=config.get("vocab_size", 30522),
            hidden_size=config.get("hidden_size", 768),
            num_hidden_layers=config.get("num_hidden_layers", 12),
            num_attention_heads=config.get("num_attention_heads", 12),
            intermediate_size=config.get("intermediate_size", 3072),
            max_position_embeddings=config.get("max_position_embeddings", 512),
            type_vocab_size=config.get("type_vocab_size", 2),
            layer_norm_eps=config.get("layer_norm_eps", 1e-12),
        )


def init_params(key, cfg: BertConfig) -> dict:
    dt = jnp.dtype(cfg.dtype)
    D, I = cfg.hidden_size, cfg.intermediate_size
    keys = jax.random.split(key, cfg.num_hidden_layers + 3)

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                / np.sqrt(shape[0])).astype(dt)

    def ln():
        return {"w": jnp.ones((D,), dt), "b": jnp.zeros((D,), dt)}

    layers = []
    for i in range(cfg.num_hidden_layers):
        ks = jax.random.split(keys[i], 6)
        layers.append({
            "q": {"w": dense(ks[0], (D, D)), "b": jnp.zeros((D,), dt)},
            "k": {"w": dense(ks[1], (D, D)), "b": jnp.zeros((D,), dt)},
            "v": {"w": dense(ks[2], (D, D)), "b": jnp.zeros((D,), dt)},
            "attn_out": {"w": dense(ks[3], (D, D)), "b": jnp.zeros((D,), dt)},
            "attn_ln": ln(),
            "inter": {"w": dense(ks[4], (D, I)), "b": jnp.zeros((I,), dt)},
            "out": {"w": dense(ks[5], (I, D)), "b": jnp.zeros((D,), dt)},
            "out_ln": ln(),
        })
    return {
        "word_emb": (jax.random.normal(keys[-3], (cfg.vocab_size, D),
                                       jnp.float32) * 0.02).astype(dt),
        "pos_emb": (jax.random.normal(keys[-2], (cfg.max_position_embeddings,
                                                 D), jnp.float32)
                    * 0.02).astype(dt),
        "type_emb": (jax.random.normal(keys[-1], (cfg.type_vocab_size, D),
                                       jnp.float32) * 0.02).astype(dt),
        "emb_ln": ln(),
        "layers": layers,
    }


def param_shardings(cfg: BertConfig, mesh: Mesh) -> dict:
    tp = int(mesh.shape.get("tp", 1))

    def sh(*spec):
        return NamedSharding(mesh, P(*spec))

    def ln():
        return {"w": sh(None), "b": sh(None)}

    ok_d = cfg.hidden_size % tp == 0
    ok_i = cfg.intermediate_size % tp == 0
    layer = {
        "q": {"w": sh(None, "tp") if ok_d else sh(None, None), "b": sh(None)},
        "k": {"w": sh(None, "tp") if ok_d else sh(None, None), "b": sh(None)},
        "v": {"w": sh(None, "tp") if ok_d else sh(None, None), "b": sh(None)},
        "attn_out": {"w": sh("tp", None) if ok_d else sh(None, None),
                     "b": sh(None)},
        "attn_ln": ln(),
        "inter": {"w": sh(None, "tp") if ok_i else sh(None, None),
                  "b": sh(None)},
        "out": {"w": sh("tp", None) if ok_i else sh(None, None),
                "b": sh(None)},
        "out_ln": ln(),
    }
    return {
        "word_emb": sh(None, None),
        "pos_emb": sh(None, None),
        "type_emb": sh(None, None),
        "emb_ln": ln(),
        "layers": [dict(layer) for _ in range(cfg.num_hidden_layers)],
    }


def encode(params, tokens, cfg: BertConfig, attention_mask=None,
           token_type_ids=None, mesh: Mesh | None = None):
    """tokens [B, T] → last hidden state [B, T, D]."""
    del mesh
    B, T = tokens.shape
    eps = cfg.layer_norm_eps
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(tokens)
    x = (params["word_emb"][tokens] + params["pos_emb"][jnp.arange(T)]
         + params["type_emb"][token_type_ids])
    x = layer_norm(x, params["emb_ln"]["w"], params["emb_ln"]["b"], eps)
    H = cfg.num_attention_heads
    hd = cfg.hidden_size // H
    if attention_mask is None:
        bias = jnp.zeros((B, 1, 1, T), jnp.float32)
    else:
        bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -1e30)
    for layer in params["layers"]:
        q = (x @ layer["q"]["w"] + layer["q"]["b"]).reshape(B, T, H, hd)
        k = (x @ layer["k"]["w"] + layer["k"]["b"]).reshape(B, T, H, hd)
        v = (x @ layer["v"]["w"] + layer["v"]["b"]).reshape(B, T, H, hd)
        if attention_mask is None and _use_flash():
            # bidirectional full-length attention maps to the fused
            # kernel directly; per-example masks keep the einsum path
            # (they need per-batch validity the kernel does not model)
            from demodel_tpu.ops.flash_attention import flash_attention

            a = flash_attention(q, k, v, causal=False).reshape(B, T, -1)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * hd ** -0.5
            scores = scores.astype(jnp.float32) + bias
            probs = jax.nn.softmax(scores, -1).astype(x.dtype)
            a = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, -1)
        a = a @ layer["attn_out"]["w"] + layer["attn_out"]["b"]
        x = layer_norm(x + a, layer["attn_ln"]["w"], layer["attn_ln"]["b"],
                       eps)
        h = jax.nn.gelu(x @ layer["inter"]["w"] + layer["inter"]["b"],
                        approximate=False)
        h = h @ layer["out"]["w"] + layer["out"]["b"]
        x = layer_norm(x + h, layer["out_ln"]["w"], layer["out_ln"]["b"], eps)
    return x
