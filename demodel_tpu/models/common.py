"""Shared numerics for the model families.

Norm statistics run in float32 regardless of activation dtype: bf16 mean/
variance across a wide hidden axis loses enough mantissa to shift logits —
the standard TPU-stable recipe (compute stats in fp32, scale in the
activation dtype).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x, weight, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    scale = lax.rsqrt((xf * xf).mean(axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * weight


def layer_norm(x, weight, bias, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    return y.astype(x.dtype) * weight + bias


def use_flash_attention() -> bool:
    """DEMODEL_FLASH_ATTN=1 routes model attention through the fused
    pallas kernel (ops/flash_attention.py). Default off: the einsum path
    lets XLA fuse freely at short sequence; flash wins once the score
    tensor — or the GQA-repeated KV cache — dominates HBM."""
    import os

    return os.environ.get("DEMODEL_FLASH_ATTN", "").strip().lower() in (
        "1", "true", "yes", "on")
