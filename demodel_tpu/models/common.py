"""Shared numerics for the model families.

Norm statistics run in float32 regardless of activation dtype: bf16 mean/
variance across a wide hidden axis loses enough mantissa to shift logits —
the standard TPU-stable recipe (compute stats in fp32, scale in the
activation dtype).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x, weight, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    scale = lax.rsqrt((xf * xf).mean(axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * weight


def layer_norm(x, weight, bias, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    return y.astype(x.dtype) * weight + bias


def use_flash_attention() -> bool:
    """Route model attention through the fused pallas kernel
    (ops/flash_attention.py)? DEMODEL_FLASH_ATTN forces either way;
    unset, the default is ON on a TPU backend once the committed on-chip
    parity record exists (ops/flash_default.py — VERDICT r4 #2), OFF
    elsewhere: the einsum path lets XLA fuse freely at short sequence,
    flash wins once the score tensor or GQA-repeated KV cache dominates
    HBM."""
    from demodel_tpu.ops.flash_default import use_flash_attention as _p

    return _p()
