"""GPT-2 family: learned positions, pre-LN blocks, fused QKV, tied head.

Checkpoint parity with HF ``transformers`` GPT2LMHeadModel is tested in
tests/test_hf_models.py (the HF Conv1D stores weights in ``x @ W``
orientation, which is exactly how this forward consumes them — no
transposes on the load path)."""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from demodel_tpu.models.common import layer_norm, use_flash_attention as _use_flash


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    dtype: str = "float32"

    @classmethod
    def tiny(cls) -> "GPT2Config":
        return cls(vocab_size=256, n_positions=64, n_embd=64, n_layer=2,
                   n_head=4)

    @classmethod
    def from_hf(cls, config: dict) -> "GPT2Config":
        return cls(
            vocab_size=config.get("vocab_size", 50257),
            n_positions=config.get("n_positions", 1024),
            n_embd=config.get("n_embd", 768),
            n_layer=config.get("n_layer", 12),
            n_head=config.get("n_head", 12),
            layer_norm_epsilon=config.get("layer_norm_epsilon", 1e-5),
        )


def init_params(key, cfg: GPT2Config) -> dict:
    dt = jnp.dtype(cfg.dtype)
    D = cfg.n_embd
    keys = jax.random.split(key, cfg.n_layer + 2)

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                / np.sqrt(shape[0])).astype(dt)

    layers = []
    for i in range(cfg.n_layer):
        ks = jax.random.split(keys[i], 4)
        layers.append({
            "ln_1": {"w": jnp.ones((D,), dt), "b": jnp.zeros((D,), dt)},
            "c_attn": {"w": dense(ks[0], (D, 3 * D)),
                       "b": jnp.zeros((3 * D,), dt)},
            "c_proj": {"w": dense(ks[1], (D, D)), "b": jnp.zeros((D,), dt)},
            "ln_2": {"w": jnp.ones((D,), dt), "b": jnp.zeros((D,), dt)},
            "mlp_fc": {"w": dense(ks[2], (D, 4 * D)),
                       "b": jnp.zeros((4 * D,), dt)},
            "mlp_proj": {"w": dense(ks[3], (4 * D, D)),
                         "b": jnp.zeros((D,), dt)},
        })
    return {
        "wte": (jax.random.normal(keys[-2], (cfg.vocab_size, D), jnp.float32)
                * 0.02).astype(dt),
        "wpe": (jax.random.normal(keys[-1], (cfg.n_positions, D), jnp.float32)
                * 0.01).astype(dt),
        "layers": layers,
        "ln_f": {"w": jnp.ones((D,), dt), "b": jnp.zeros((D,), dt)},
    }


def param_shardings(cfg: GPT2Config, mesh: Mesh) -> dict:
    tp = int(mesh.shape.get("tp", 1))

    def sh(*spec):
        return NamedSharding(mesh, P(*spec))

    def ln():
        return {"w": sh(None), "b": sh(None)}

    col_ok = (3 * cfg.n_embd) % tp == 0 and (4 * cfg.n_embd) % tp == 0
    layer = {
        "ln_1": ln(),
        "c_attn": {"w": sh(None, "tp") if col_ok else sh(None, None),
                   "b": sh(None)},
        "c_proj": {"w": sh("tp", None) if cfg.n_embd % tp == 0 else sh(None, None),
                   "b": sh(None)},
        "ln_2": ln(),
        "mlp_fc": {"w": sh(None, "tp") if col_ok else sh(None, None),
                   "b": sh(None)},
        "mlp_proj": {"w": sh("tp", None) if col_ok else sh(None, None),
                     "b": sh(None)},
    }
    return {
        "wte": sh(None, None),
        "wpe": sh(None, None),
        "layers": [dict(layer) for _ in range(cfg.n_layer)],
        "ln_f": ln(),
    }


def forward(params, tokens, cfg: GPT2Config, mesh: Mesh | None = None):
    """tokens [B, T] → logits [B, T, V] (head tied to wte, as HF)."""
    del mesh  # dense attention; sharding comes from param placement
    B, T = tokens.shape
    eps = cfg.layer_norm_epsilon
    x = params["wte"][tokens] + params["wpe"][jnp.arange(T)]
    H = cfg.n_head
    hd = cfg.n_embd // H
    mask = jnp.tril(jnp.ones((T, T), bool))
    for layer in params["layers"]:
        h = layer_norm(x, layer["ln_1"]["w"], layer["ln_1"]["b"], eps)
        qkv = h @ layer["c_attn"]["w"] + layer["c_attn"]["b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, hd)
        k = k.reshape(B, T, H, hd)
        v = v.reshape(B, T, H, hd)
        if _use_flash():
            from demodel_tpu.ops.flash_attention import flash_attention

            a = flash_attention(q, k, v, causal=True).reshape(B, T, -1)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * hd ** -0.5
            scores = jnp.where(mask[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores.astype(jnp.float32),
                                   -1).astype(x.dtype)
            a = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, -1)
        x = x + (a @ layer["c_proj"]["w"] + layer["c_proj"]["b"])
        h = layer_norm(x, layer["ln_2"]["w"], layer["ln_2"]["b"], eps)
        h = jax.nn.gelu(h @ layer["mlp_fc"]["w"] + layer["mlp_fc"]["b"],
                        approximate=True)
        x = x + (h @ layer["mlp_proj"]["w"] + layer["mlp_proj"]["b"])
    x = layer_norm(x, params["ln_f"]["w"], params["ln_f"]["b"], eps)
    return x @ params["wte"].T  # tied head
