"""HF-checkpoint → params-tree mapping for the model families.

Consumes a flat ``{tensor_name: array}`` (a sink :class:`Placement`'s
arrays, or host numpy) holding a ``transformers``-layout state dict and
rebuilds each family's params pytree. torch ``nn.Linear`` stores
``[out, in]`` — those transpose on the way in; GPT-2's Conv1D already
stores ``[in, out]`` and loads verbatim. Optional name prefixes
("model.", "transformer.", "bert.") are stripped automatically.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from demodel_tpu.models.bert import BertConfig
from demodel_tpu.models.gpt2 import GPT2Config
from demodel_tpu.models.llama import LlamaConfig

_PREFIXES = ("", "model.", "transformer.", "bert.")


class _Weights:
    def __init__(self, weights: dict):
        self.w = weights

    def get(self, name: str, transpose: bool = False):
        for p in _PREFIXES:
            if p + name in self.w:
                arr = jnp.asarray(np.asarray(self.w[p + name]))
                return arr.T if transpose else arr
        raise KeyError(f"checkpoint has no tensor {name!r} "
                       f"(tried prefixes {_PREFIXES})")

    def has(self, name: str) -> bool:
        return any(p + name in self.w for p in _PREFIXES)


def load_llama_params(weights: dict, cfg: LlamaConfig) -> dict:
    w = _Weights(weights)
    layers = []
    for i in range(cfg.num_hidden_layers):
        pre = f"layers.{i}."
        layers.append({
            "attn_norm": w.get(pre + "input_layernorm.weight"),
            "q_proj": w.get(pre + "self_attn.q_proj.weight", transpose=True),
            "k_proj": w.get(pre + "self_attn.k_proj.weight", transpose=True),
            "v_proj": w.get(pre + "self_attn.v_proj.weight", transpose=True),
            "o_proj": w.get(pre + "self_attn.o_proj.weight", transpose=True),
            "mlp_norm": w.get(pre + "post_attention_layernorm.weight"),
            "gate_proj": w.get(pre + "mlp.gate_proj.weight", transpose=True),
            "up_proj": w.get(pre + "mlp.up_proj.weight", transpose=True),
            "down_proj": w.get(pre + "mlp.down_proj.weight", transpose=True),
        })
    embed = w.get("embed_tokens.weight")
    if w.has("lm_head.weight"):
        head = w.get("lm_head.weight", transpose=True)
    else:  # tied embeddings
        head = embed.T
    return {
        "embed": embed,
        "layers": layers,
        "final_norm": w.get("norm.weight"),
        "lm_head": head,
    }


def load_gpt2_params(weights: dict, cfg: GPT2Config) -> dict:
    w = _Weights(weights)
    layers = []
    for i in range(cfg.n_layer):
        pre = f"h.{i}."
        layers.append({
            "ln_1": {"w": w.get(pre + "ln_1.weight"),
                     "b": w.get(pre + "ln_1.bias")},
            "c_attn": {"w": w.get(pre + "attn.c_attn.weight"),
                       "b": w.get(pre + "attn.c_attn.bias")},
            "c_proj": {"w": w.get(pre + "attn.c_proj.weight"),
                       "b": w.get(pre + "attn.c_proj.bias")},
            "ln_2": {"w": w.get(pre + "ln_2.weight"),
                     "b": w.get(pre + "ln_2.bias")},
            "mlp_fc": {"w": w.get(pre + "mlp.c_fc.weight"),
                       "b": w.get(pre + "mlp.c_fc.bias")},
            "mlp_proj": {"w": w.get(pre + "mlp.c_proj.weight"),
                         "b": w.get(pre + "mlp.c_proj.bias")},
        })
    return {
        "wte": w.get("wte.weight"),
        "wpe": w.get("wpe.weight"),
        "layers": layers,
        "ln_f": {"w": w.get("ln_f.weight"), "b": w.get("ln_f.bias")},
    }


def load_bert_params(weights: dict, cfg: BertConfig) -> dict:
    w = _Weights(weights)

    def lin(name):
        return {"w": w.get(name + ".weight", transpose=True),
                "b": w.get(name + ".bias")}

    def ln(name):
        return {"w": w.get(name + ".weight"), "b": w.get(name + ".bias")}

    layers = []
    for i in range(cfg.num_hidden_layers):
        pre = f"encoder.layer.{i}."
        layers.append({
            "q": lin(pre + "attention.self.query"),
            "k": lin(pre + "attention.self.key"),
            "v": lin(pre + "attention.self.value"),
            "attn_out": lin(pre + "attention.output.dense"),
            "attn_ln": ln(pre + "attention.output.LayerNorm"),
            "inter": lin(pre + "intermediate.dense"),
            "out": lin(pre + "output.dense"),
            "out_ln": ln(pre + "output.LayerNorm"),
        })
    return {
        "word_emb": w.get("embeddings.word_embeddings.weight"),
        "pos_emb": w.get("embeddings.position_embeddings.weight"),
        "type_emb": w.get("embeddings.token_type_embeddings.weight"),
        "emb_ln": ln("embeddings.LayerNorm"),
        "layers": layers,
    }
