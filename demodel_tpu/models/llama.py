"""Llama family — the flagship model of the delivery stack.

TPU-first design, not a port: pure-functional params pytree, static shapes
everywhere (jit/pjit-safe), GQA attention with HF's rotate-half RoPE
convention (checkpoint parity is tested against ``transformers``' reference
implementation in tests/test_hf_models.py), sharding expressed as
``NamedSharding`` trees over a ``Mesh`` — tensor parallel on the hidden
axes, sequence/context parallel attention as an exact ``ppermute`` ring
(:mod:`demodel_tpu.ops.ring_attention`) when the mesh carries an ``sp``
axis. The train step is jit-compiled once; XLA inserts the ICI collectives
implied by the shardings.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from demodel_tpu.models.common import rms_norm, use_flash_attention as _use_flash
from demodel_tpu.ops.ring_attention import (
    dense_attention,
    ring_attention_sharded,
)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """Test/driver-sized config: real GQA (4 q heads per kv head)."""
        return cls(vocab_size=256, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=8,
                   num_key_value_heads=2)

    @classmethod
    def from_hf(cls, config: dict) -> "LlamaConfig":
        return cls(
            vocab_size=config.get("vocab_size", 32000),
            hidden_size=config.get("hidden_size", 4096),
            intermediate_size=config.get("intermediate_size", 11008),
            num_hidden_layers=config.get("num_hidden_layers", 32),
            num_attention_heads=config.get("num_attention_heads", 32),
            num_key_value_heads=config.get(
                "num_key_value_heads", config.get("num_attention_heads", 32)),
            rope_theta=config.get("rope_theta", 10000.0),
            rms_norm_eps=config.get("rms_norm_eps", 1e-6),
        )


# ------------------------------------------------------------------ params


def init_params(key, cfg: LlamaConfig) -> dict:
    dt = jnp.dtype(cfg.dtype)
    D, I, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    hd = cfg.head_dim
    H, Hkv = cfg.num_attention_heads, cfg.num_key_value_heads
    keys = jax.random.split(key, cfg.num_hidden_layers + 2)

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                / np.sqrt(shape[0])).astype(dt)

    layers = []
    for i in range(cfg.num_hidden_layers):
        ks = jax.random.split(keys[i], 7)
        layers.append({
            "attn_norm": jnp.ones((D,), dt),
            "q_proj": dense(ks[0], (D, H * hd)),
            "k_proj": dense(ks[1], (D, Hkv * hd)),
            "v_proj": dense(ks[2], (D, Hkv * hd)),
            "o_proj": dense(ks[3], (H * hd, D)),
            "mlp_norm": jnp.ones((D,), dt),
            "gate_proj": dense(ks[4], (D, I)),
            "up_proj": dense(ks[5], (D, I)),
            "down_proj": dense(ks[6], (I, D)),
        })
    return {
        "embed": (jax.random.normal(keys[-2], (V, D), jnp.float32)
                  * 0.02).astype(dt),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
        "lm_head": dense(keys[-1], (D, V)),
    }


def param_shardings(cfg: LlamaConfig, mesh: Mesh) -> dict:
    """NamedSharding tree matching :func:`init_params`: column-parallel
    in-projections, row-parallel out-projections over ``tp``; norms
    replicated; embeddings vocab-sharded when divisible."""
    tp = int(mesh.shape.get("tp", 1))

    def sh(*spec):
        return NamedSharding(mesh, P(*spec))

    col = sh(None, "tp")   # [D, out] split on out
    row = sh("tp", None)   # [in, D] split on in
    rep1 = sh(None)
    layer = {
        "attn_norm": rep1,
        "q_proj": col if (cfg.num_attention_heads * cfg.head_dim) % tp == 0 else sh(None, None),
        "k_proj": col if (cfg.num_key_value_heads * cfg.head_dim) % tp == 0 else sh(None, None),
        "v_proj": col if (cfg.num_key_value_heads * cfg.head_dim) % tp == 0 else sh(None, None),
        "o_proj": row if (cfg.num_attention_heads * cfg.head_dim) % tp == 0 else sh(None, None),
        "mlp_norm": rep1,
        "gate_proj": col if cfg.intermediate_size % tp == 0 else sh(None, None),
        "up_proj": col if cfg.intermediate_size % tp == 0 else sh(None, None),
        "down_proj": row if cfg.intermediate_size % tp == 0 else sh(None, None),
    }
    return {
        "embed": sh("tp", None) if cfg.vocab_size % tp == 0 else sh(None, None),
        "layers": [dict(layer) for _ in range(cfg.num_hidden_layers)],
        "final_norm": rep1,
        "lm_head": sh(None, "tp") if cfg.vocab_size % tp == 0 else sh(None, None),
    }


# ------------------------------------------------------------------- rope


def _rope(x, positions, theta: float):
    """HF rotate-half convention: pairs are (i, i + hd/2)."""
    B, T, H, hd = x.shape
    inv = 1.0 / (theta ** (np.arange(0, hd, 2, dtype=np.float32) / hd))
    ang = positions[..., None].astype(jnp.float32) * inv  # [B,T,hd/2]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., : hd // 2], x[..., hd // 2:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


# ----------------------------------------------------------------- forward


def _head_align(x, mesh: Mesh | None):
    """Constrain [B,T,H,hd] to a HEAD-aligned tp sharding (or replicate
    when the head count doesn't divide tp). Without this, a column-sharded
    projection reshape leaves each shard holding *half a head*, and the
    rotate-half slice+concat inside :func:`_rope` crosses the shard
    boundary — a combination this jax/XLA-CPU build miscompiles under
    multi-axis meshes (wrong VALUES, not just wrong layout; the
    sp-mesh odd-prompt decode divergence ROADMAP carried). Head-aligned
    shards are also the layout TP attention wants: every later op in the
    cache path is per-head."""
    if mesh is None:
        return x
    tp = int(mesh.shape.get("tp", 1))
    if tp <= 1:
        return x
    H = x.shape[2]
    spec = P(None, None, "tp", None) if H % tp == 0 else P(None, None, None, None)
    return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _attn(layer, x, cfg: LlamaConfig, positions, mesh: Mesh | None,
          kv_cache=None, cache_pos=None):
    B, T, D = x.shape
    hd = cfg.head_dim
    H, Hkv = cfg.num_attention_heads, cfg.num_key_value_heads
    q = (x @ layer["q_proj"]).reshape(B, T, H, hd)
    k = (x @ layer["k_proj"]).reshape(B, T, Hkv, hd)
    v = (x @ layer["v_proj"]).reshape(B, T, Hkv, hd)
    if kv_cache is not None:
        # cached decode/prefill: re-align shards on the head axis BEFORE
        # the rotate-half slicing (see _head_align). The ring branch
        # manages its own sequence sharding and must not be re-constrained.
        q = _head_align(q, mesh)
        k = _head_align(k, mesh)
        v = _head_align(v, mesh)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)

    new_cache = None
    if kv_cache is not None:
        ck, cv = kv_cache
        ck = lax.dynamic_update_slice(ck, k, (0, cache_pos, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, cache_pos, 0, 0))
        new_cache = (ck, cv)
        if _use_flash():
            # fused decode: no repeat of the whole cache across query
            # heads, and K blocks past the filled prefix are skipped —
            # cost scales with cache_pos + T, not the cache capacity
            from demodel_tpu.ops.flash_attention import flash_attention

            out = flash_attention(q, ck, cv, kv_len=cache_pos + T,
                                  causal=True)
        else:
            S = ck.shape[1]
            rep = H // Hkv
            kk = jnp.repeat(ck, rep, axis=2)
            vv = jnp.repeat(cv, rep, axis=2)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) * hd ** -0.5
            kpos = jnp.arange(S)
            qpos = cache_pos + jnp.arange(T)
            mask = kpos[None, :] <= qpos[:, None]
            scores = jnp.where(mask[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores.astype(jnp.float32),
                                   axis=-1).astype(q.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, vv)
    elif mesh is not None and int(mesh.shape.get("sp", 1)) > 1:
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
    elif _use_flash():
        # fused pallas path: no (B,H,T,T) score tensor in HBM, no
        # materialized GQA repeat (ops/flash_attention.py); backward
        # recomputes the reference, so training still differentiates
        from demodel_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, causal=True)
    else:
        out = dense_attention(q, k, v, causal=True)
    out = out.reshape(B, T, H * hd) @ layer["o_proj"]
    return out, new_cache


def _block(layer, x, cfg, positions, mesh, kv_cache=None, cache_pos=None):
    h, new_cache = _attn(layer, rms_norm(x, layer["attn_norm"],
                                         cfg.rms_norm_eps),
                         cfg, positions, mesh, kv_cache, cache_pos)
    x = x + h
    y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    y = (jax.nn.silu(y @ layer["gate_proj"]) * (y @ layer["up_proj"])) \
        @ layer["down_proj"]
    return x + y, new_cache


def _seq_constraint(x, mesh: Mesh | None):
    if mesh is not None and int(mesh.shape.get("sp", 1)) > 1:
        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", "sp", None)))
    return x


def forward(params, tokens, cfg: LlamaConfig, mesh: Mesh | None = None):
    """tokens [B, T] int32 → logits [B, T, V]."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    x = params["embed"][tokens]
    x = _seq_constraint(x, mesh)
    for layer in params["layers"]:
        x, _ = _block(layer, x, cfg, positions, mesh)
        x = _seq_constraint(x, mesh)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return x @ params["lm_head"]


# ------------------------------------------------------------ decode path


def init_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=None):
    dt = jnp.dtype(dtype or cfg.dtype)
    hd = cfg.head_dim
    return [
        (jnp.zeros((batch, max_len, cfg.num_key_value_heads, hd), dt),
         jnp.zeros((batch, max_len, cfg.num_key_value_heads, hd), dt))
        for _ in range(cfg.num_hidden_layers)
    ]


def forward_with_cache(params, tokens, cfg: LlamaConfig, cache, pos,
                       mesh: Mesh | None = None):
    """Incremental forward: ``tokens`` [B, T] appended at ``pos`` (prefill
    with T>1, decode with T=1). Returns (logits, new_cache). ``mesh``
    (when the params are sharded over one) keeps the projection shards
    head-aligned through RoPE — see :func:`_head_align`."""
    B, T = tokens.shape
    positions = pos + jnp.broadcast_to(jnp.arange(T), (B, T))
    x = params["embed"][tokens]
    new_cache = []
    for layer, kv in zip(params["layers"], cache):
        x, nkv = _block(layer, x, cfg, positions, mesh, kv_cache=kv,
                        cache_pos=pos)
        new_cache.append(nkv)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return x @ params["lm_head"], new_cache


def step_prefill(params, tokens, cfg: LlamaConfig, mesh: Mesh | None = None):
    """Prefill leg of the serving plane: ``tokens`` [B, T] (one sequence,
    or a few of EQUAL length) → ``(last_logits [B, V], kv)`` where ``kv``
    is the per-layer ``(k, v)`` pair, each [B, T, Hkv, hd] — exactly the
    prompt's keys/values, which the caller pages out into pool blocks
    (:mod:`demodel_tpu.serve.kvcache`). The cache is sized to the prompt,
    so this is :func:`forward_with_cache` with nothing left over."""
    B, T = tokens.shape
    cache = init_cache(cfg, B, T)
    logits, kv = forward_with_cache(params, tokens, cfg, cache, 0, mesh=mesh)
    return logits[:, -1], kv


def step_decode(params, tokens, cfg: LlamaConfig, cache, lengths,
                mesh: Mesh | None = None):
    """One continuous-batching decode step over a RAGGED batch.

    ``tokens`` [B] int32 — the last sampled token of each running
    sequence; ``cache`` per-layer ``(k, v)``, each [B, S, Hkv, hd] — a
    dense gather of each sequence's paged blocks (rows at or past
    ``lengths[b]`` are stale pool bytes and are masked out here);
    ``lengths`` [B] int32 — filled prefix per sequence, so the fed token
    sits at position ``lengths[b]`` (positions need not agree across the
    batch — that is the whole point). Returns ``(logits [B, V], new_kv)``
    with ``new_kv`` per-layer ``(k, v)`` each [B, 1, Hkv, hd], written
    back into the pool by the caller: the pool owns placement, the model
    never sees a block table. Rows padded up to a jit bucket ride along
    with ``lengths[b] == 0`` (they attend only to themselves) and are
    dropped by the caller."""
    B = tokens.shape[0]
    hd = cfg.head_dim
    H, Hkv = cfg.num_attention_heads, cfg.num_key_value_heads
    positions = lengths[:, None]                      # [B, 1]
    x = params["embed"][tokens[:, None]]              # [B, 1, D]
    new_kv = []
    for layer, (ck, cv) in zip(params["layers"], cache):
        h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q = (h @ layer["q_proj"]).reshape(B, 1, H, hd)
        k = (h @ layer["k_proj"]).reshape(B, 1, Hkv, hd)
        v = (h @ layer["v_proj"]).reshape(B, 1, Hkv, hd)
        q = _head_align(q, mesh)
        k = _head_align(k, mesh)
        v = _head_align(v, mesh)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        new_kv.append((k, v))
        S = ck.shape[1]
        kk = jnp.concatenate([ck, k], axis=1)         # [B, S+1, Hkv, hd]
        vv = jnp.concatenate([cv, v], axis=1)
        rep = H // Hkv
        kk = jnp.repeat(kk, rep, axis=2)
        vv = jnp.repeat(vv, rep, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) * hd ** -0.5
        kpos = jnp.arange(S + 1)
        valid = (kpos[None, :] < lengths[:, None]) | (kpos[None, :] == S)
        scores = jnp.where(valid[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(q.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, vv)
        x = x + out.reshape(B, 1, H * hd) @ layer["o_proj"]
        y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        y = (jax.nn.silu(y @ layer["gate_proj"]) * (y @ layer["up_proj"])) \
            @ layer["down_proj"]
        x = x + y
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return (x @ params["lm_head"])[:, 0], new_kv


def generate(params, cfg: LlamaConfig, prompt, max_new_tokens: int,
             temperature: float = 0.0, key=None, mesh: Mesh | None = None):
    """Autoregressive decode: prefill the prompt once, then one cached
    step per token (jitted, static shapes). temperature 0 → greedy."""
    prompt = jnp.asarray(prompt)
    if prompt.ndim == 1:
        prompt = prompt[None]
    B, T0 = prompt.shape
    max_len = T0 + max_new_tokens
    cache = init_cache(cfg, B, max_len)
    if key is None:
        key = jax.random.key(0)

    prefill = jax.jit(
        lambda p, t, c: forward_with_cache(p, t, cfg, c, 0, mesh=mesh))
    logits, cache = prefill(params, prompt, cache)
    last = logits[:, -1]

    @jax.jit
    def step(carry, _):
        last, cache, pos, k = carry
        k, sub = jax.random.split(k)
        if temperature > 0:
            tok = jax.random.categorical(sub, last / temperature, axis=-1)
        else:
            tok = jnp.argmax(last, axis=-1)
        tok = tok.astype(jnp.int32)
        logits, cache = forward_with_cache(params, tok[:, None], cfg, cache,
                                           pos, mesh=mesh)
        return (logits[:, -1], cache, pos + 1, k), tok

    carry = (last, cache, jnp.int32(T0), key)
    out_toks = []
    for _ in range(max_new_tokens):
        carry, tok = step(carry, None)
        out_toks.append(tok)
    return jnp.stack(out_toks, axis=1)


# -------------------------------------------------------------- train step


def loss_fn(params, tokens, cfg: LlamaConfig, mesh: Mesh | None = None):
    """Next-token cross entropy (fp32 logits for the softmax)."""
    logits = forward(params, tokens[:, :-1], cfg, mesh).astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()


def make_train_step(cfg: LlamaConfig, mesh: Mesh | None = None,
                    lr: float = 1e-3, momentum: float = 0.9):
    """(init_opt, train_step) with a momentum-SGD state that mirrors the
    params tree leaf-for-leaf — the same sharding tree places both."""

    def init_opt(params):
        return jax.tree.map(jnp.zeros_like, params)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)
        new_opt = jax.tree.map(lambda m, g: momentum * m + g, opt_state, grads)
        new_params = jax.tree.map(lambda p, m: p - lr * m, params, new_opt)
        return new_params, new_opt, loss

    return init_opt, jax.jit(train_step)
