"""Llama family — the flagship model of the delivery stack.

TPU-first design, not a port: pure-functional params pytree, static shapes
everywhere (jit/pjit-safe), GQA attention with HF's rotate-half RoPE
convention (checkpoint parity is tested against ``transformers``' reference
implementation in tests/test_hf_models.py), sharding expressed as
``NamedSharding`` trees over a ``Mesh`` — tensor parallel on the hidden
axes, sequence/context parallel attention as an exact ``ppermute`` ring
(:mod:`demodel_tpu.ops.ring_attention`) when the mesh carries an ``sp``
axis. The train step is jit-compiled once; XLA inserts the ICI collectives
implied by the shardings.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from demodel_tpu.models.common import rms_norm, use_flash_attention as _use_flash
from demodel_tpu.ops.ring_attention import (
    dense_attention,
    ring_attention_sharded,
)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """Test/driver-sized config: real GQA (4 q heads per kv head)."""
        return cls(vocab_size=256, hidden_size=64, intermediate_size=128,
                   num_hidden_layers=2, num_attention_heads=8,
                   num_key_value_heads=2)

    @classmethod
    def from_hf(cls, config: dict) -> "LlamaConfig":
        return cls(
            vocab_size=config.get("vocab_size", 32000),
            hidden_size=config.get("hidden_size", 4096),
            intermediate_size=config.get("intermediate_size", 11008),
            num_hidden_layers=config.get("num_hidden_layers", 32),
            num_attention_heads=config.get("num_attention_heads", 32),
            num_key_value_heads=config.get(
                "num_key_value_heads", config.get("num_attention_heads", 32)),
            rope_theta=config.get("rope_theta", 10000.0),
            rms_norm_eps=config.get("rms_norm_eps", 1e-6),
        )


# ------------------------------------------------------------------ params


def init_params(key, cfg: LlamaConfig) -> dict:
    dt = jnp.dtype(cfg.dtype)
    D, I, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    hd = cfg.head_dim
    H, Hkv = cfg.num_attention_heads, cfg.num_key_value_heads
    keys = jax.random.split(key, cfg.num_hidden_layers + 2)

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                / np.sqrt(shape[0])).astype(dt)

    layers = []
    for i in range(cfg.num_hidden_layers):
        ks = jax.random.split(keys[i], 7)
        layers.append({
            "attn_norm": jnp.ones((D,), dt),
            "q_proj": dense(ks[0], (D, H * hd)),
            "k_proj": dense(ks[1], (D, Hkv * hd)),
            "v_proj": dense(ks[2], (D, Hkv * hd)),
            "o_proj": dense(ks[3], (H * hd, D)),
            "mlp_norm": jnp.ones((D,), dt),
            "gate_proj": dense(ks[4], (D, I)),
            "up_proj": dense(ks[5], (D, I)),
            "down_proj": dense(ks[6], (I, D)),
        })
    return {
        "embed": (jax.random.normal(keys[-2], (V, D), jnp.float32)
                  * 0.02).astype(dt),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
        "lm_head": dense(keys[-1], (D, V)),
    }


def param_shardings(cfg: LlamaConfig, mesh: Mesh) -> dict:
    """NamedSharding tree matching :func:`init_params`: column-parallel
    in-projections, row-parallel out-projections over ``tp``; norms
    replicated; embeddings vocab-sharded when divisible."""
    tp = int(mesh.shape.get("tp", 1))

    def sh(*spec):
        return NamedSharding(mesh, P(*spec))

    col = sh(None, "tp")   # [D, out] split on out
    row = sh("tp", None)   # [in, D] split on in
    rep1 = sh(None)
    layer = {
        "attn_norm": rep1,
        "q_proj": col if (cfg.num_attention_heads * cfg.head_dim) % tp == 0 else sh(None, None),
        "k_proj": col if (cfg.num_key_value_heads * cfg.head_dim) % tp == 0 else sh(None, None),
        "v_proj": col if (cfg.num_key_value_heads * cfg.head_dim) % tp == 0 else sh(None, None),
        "o_proj": row if (cfg.num_attention_heads * cfg.head_dim) % tp == 0 else sh(None, None),
        "mlp_norm": rep1,
        "gate_proj": col if cfg.intermediate_size % tp == 0 else sh(None, None),
        "up_proj": col if cfg.intermediate_size % tp == 0 else sh(None, None),
        "down_proj": row if cfg.intermediate_size % tp == 0 else sh(None, None),
    }
    return {
        "embed": sh("tp", None) if cfg.vocab_size % tp == 0 else sh(None, None),
        "layers": [dict(layer) for _ in range(cfg.num_hidden_layers)],
        "final_norm": rep1,
        "lm_head": sh(None, "tp") if cfg.vocab_size % tp == 0 else sh(None, None),
    }


# ------------------------------------------------------------------- rope


def _rope(x, positions, theta: float):
    """HF rotate-half convention: pairs are (i, i + hd/2)."""
    B, T, H, hd = x.shape
    inv = 1.0 / (theta ** (np.arange(0, hd, 2, dtype=np.float32) / hd))
    ang = positions[..., None].astype(jnp.float32) * inv  # [B,T,hd/2]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., : hd // 2], x[..., hd // 2:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


# ----------------------------------------------------------------- forward


def _attn(layer, x, cfg: LlamaConfig, positions, mesh: Mesh | None,
          kv_cache=None, cache_pos=None):
    B, T, D = x.shape
    hd = cfg.head_dim
    H, Hkv = cfg.num_attention_heads, cfg.num_key_value_heads
    q = (x @ layer["q_proj"]).reshape(B, T, H, hd)
    k = (x @ layer["k_proj"]).reshape(B, T, Hkv, hd)
    v = (x @ layer["v_proj"]).reshape(B, T, Hkv, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)

    new_cache = None
    if kv_cache is not None:
        ck, cv = kv_cache
        ck = lax.dynamic_update_slice(ck, k, (0, cache_pos, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, cache_pos, 0, 0))
        new_cache = (ck, cv)
        if _use_flash():
            # fused decode: no repeat of the whole cache across query
            # heads, and K blocks past the filled prefix are skipped —
            # cost scales with cache_pos + T, not the cache capacity
            from demodel_tpu.ops.flash_attention import flash_attention

            out = flash_attention(q, ck, cv, kv_len=cache_pos + T,
                                  causal=True)
        else:
            S = ck.shape[1]
            rep = H // Hkv
            kk = jnp.repeat(ck, rep, axis=2)
            vv = jnp.repeat(cv, rep, axis=2)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk) * hd ** -0.5
            kpos = jnp.arange(S)
            qpos = cache_pos + jnp.arange(T)
            mask = kpos[None, :] <= qpos[:, None]
            scores = jnp.where(mask[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores.astype(jnp.float32),
                                   axis=-1).astype(q.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, vv)
    elif mesh is not None and int(mesh.shape.get("sp", 1)) > 1:
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
    elif _use_flash():
        # fused pallas path: no (B,H,T,T) score tensor in HBM, no
        # materialized GQA repeat (ops/flash_attention.py); backward
        # recomputes the reference, so training still differentiates
        from demodel_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, causal=True)
    else:
        out = dense_attention(q, k, v, causal=True)
    out = out.reshape(B, T, H * hd) @ layer["o_proj"]
    return out, new_cache


def _block(layer, x, cfg, positions, mesh, kv_cache=None, cache_pos=None):
    h, new_cache = _attn(layer, rms_norm(x, layer["attn_norm"],
                                         cfg.rms_norm_eps),
                         cfg, positions, mesh, kv_cache, cache_pos)
    x = x + h
    y = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
    y = (jax.nn.silu(y @ layer["gate_proj"]) * (y @ layer["up_proj"])) \
        @ layer["down_proj"]
    return x + y, new_cache


def _seq_constraint(x, mesh: Mesh | None):
    if mesh is not None and int(mesh.shape.get("sp", 1)) > 1:
        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", "sp", None)))
    return x


def forward(params, tokens, cfg: LlamaConfig, mesh: Mesh | None = None):
    """tokens [B, T] int32 → logits [B, T, V]."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    x = params["embed"][tokens]
    x = _seq_constraint(x, mesh)
    for layer in params["layers"]:
        x, _ = _block(layer, x, cfg, positions, mesh)
        x = _seq_constraint(x, mesh)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return x @ params["lm_head"]


# ------------------------------------------------------------ decode path


def init_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=None):
    dt = jnp.dtype(dtype or cfg.dtype)
    hd = cfg.head_dim
    return [
        (jnp.zeros((batch, max_len, cfg.num_key_value_heads, hd), dt),
         jnp.zeros((batch, max_len, cfg.num_key_value_heads, hd), dt))
        for _ in range(cfg.num_hidden_layers)
    ]


def forward_with_cache(params, tokens, cfg: LlamaConfig, cache, pos):
    """Incremental forward: ``tokens`` [B, T] appended at ``pos`` (prefill
    with T>1, decode with T=1). Returns (logits, new_cache)."""
    B, T = tokens.shape
    positions = pos + jnp.broadcast_to(jnp.arange(T), (B, T))
    x = params["embed"][tokens]
    new_cache = []
    for layer, kv in zip(params["layers"], cache):
        x, nkv = _block(layer, x, cfg, positions, None, kv_cache=kv,
                        cache_pos=pos)
        new_cache.append(nkv)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return x @ params["lm_head"], new_cache


def generate(params, cfg: LlamaConfig, prompt, max_new_tokens: int,
             temperature: float = 0.0, key=None, mesh: Mesh | None = None):
    """Autoregressive decode: prefill the prompt once, then one cached
    step per token (jitted, static shapes). temperature 0 → greedy."""
    prompt = jnp.asarray(prompt)
    if prompt.ndim == 1:
        prompt = prompt[None]
    B, T0 = prompt.shape
    max_len = T0 + max_new_tokens
    cache = init_cache(cfg, B, max_len)
    if key is None:
        key = jax.random.key(0)

    prefill = jax.jit(
        lambda p, t, c: forward_with_cache(p, t, cfg, c, 0))
    logits, cache = prefill(params, prompt, cache)
    last = logits[:, -1]

    @jax.jit
    def step(carry, _):
        last, cache, pos, k = carry
        k, sub = jax.random.split(k)
        if temperature > 0:
            tok = jax.random.categorical(sub, last / temperature, axis=-1)
        else:
            tok = jnp.argmax(last, axis=-1)
        tok = tok.astype(jnp.int32)
        logits, cache = forward_with_cache(params, tok[:, None], cfg, cache,
                                           pos)
        return (logits[:, -1], cache, pos + 1, k), tok

    carry = (last, cache, jnp.int32(T0), key)
    out_toks = []
    for _ in range(max_new_tokens):
        carry, tok = step(carry, None)
        out_toks.append(tok)
    return jnp.stack(out_toks, axis=1)


# -------------------------------------------------------------- train step


def loss_fn(params, tokens, cfg: LlamaConfig, mesh: Mesh | None = None):
    """Next-token cross entropy (fp32 logits for the softmax)."""
    logits = forward(params, tokens[:, :-1], cfg, mesh).astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()


def make_train_step(cfg: LlamaConfig, mesh: Mesh | None = None,
                    lr: float = 1e-3, momentum: float = 0.9):
    """(init_opt, train_step) with a momentum-SGD state that mirrors the
    params tree leaf-for-leaf — the same sharding tree places both."""

    def init_opt(params):
        return jax.tree.map(jnp.zeros_like, params)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)
        new_opt = jax.tree.map(lambda m, g: momentum * m + g, opt_state, grads)
        new_params = jax.tree.map(lambda p, m: p - lr * m, params, new_opt)
        return new_params, new_opt, loss

    return init_opt, jax.jit(train_step)
