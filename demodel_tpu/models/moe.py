"""Mixture-of-Experts LM with expert parallelism over an ``ep`` mesh axis.

Top-1 token-choice routing with a capacity factor: overflowing tokens are
dropped (contribute zero), the standard static-shape TPU formulation — the
dispatch/combine are dense one-hot einsums that XLA lays out as all-to-alls
when the expert axis is sharded over ``ep``. Everything is shape-static and
jit-safe; no data-dependent control flow.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from demodel_tpu.models.common import rms_norm


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 256
    hidden_size: int = 64
    intermediate_size: int = 128
    num_layers: int = 2
    num_experts: int = 4
    capacity_factor: float = 1.25
    dtype: str = "float32"

    @classmethod
    def tiny(cls) -> "MoEConfig":
        return cls()


def init_params(key, cfg: MoEConfig) -> dict:
    dt = jnp.dtype(cfg.dtype)
    D, I, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    keys = jax.random.split(key, cfg.num_layers + 2)

    layers = []
    for i in range(cfg.num_layers):
        ks = jax.random.split(keys[i], 3)
        layers.append({
            "norm": jnp.ones((D,), dt),
            "router": (jax.random.normal(ks[0], (D, E), jnp.float32)
                       / np.sqrt(D)).astype(dt),
            "w_in": (jax.random.normal(ks[1], (E, D, I), jnp.float32)
                     / np.sqrt(D)).astype(dt),
            "w_out": (jax.random.normal(ks[2], (E, I, D), jnp.float32)
                      / np.sqrt(I)).astype(dt),
        })
    return {
        "embed": (jax.random.normal(keys[-2], (cfg.vocab_size, D),
                                    jnp.float32) * 0.02).astype(dt),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
        "head": (jax.random.normal(keys[-1], (D, cfg.vocab_size),
                                   jnp.float32) / np.sqrt(D)).astype(dt),
    }


def param_shardings(cfg: MoEConfig, mesh: Mesh) -> dict:
    ep = int(mesh.shape.get("ep", 1))

    def sh(*spec):
        return NamedSharding(mesh, P(*spec))

    expert_ok = cfg.num_experts % ep == 0
    layer = {
        "norm": sh(None),
        "router": sh(None, None),
        # expert weights shard on the EXPERT axis — each ep group holds its
        # experts only; dispatch rides the mesh as an all-to-all
        "w_in": sh("ep", None, None) if expert_ok else sh(None, None, None),
        "w_out": sh("ep", None, None) if expert_ok else sh(None, None, None),
    }
    return {
        "embed": sh(None, None),
        "layers": [dict(layer) for _ in range(cfg.num_layers)],
        "final_norm": sh(None),
        "head": sh(None, None),
    }


def route(logits, capacity: int):
    """Top-1 routing with per-expert capacity.

    logits [N, E] → (combine [N, E, C], dispatch bool [N, E, C]).
    Invariants (tested): each token occupies ≤1 slot; each (expert, slot)
    holds ≤1 token; tokens beyond an expert's capacity are dropped.
    """
    N, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)      # [N, E]
    # position of each token within its expert's queue (arrival order)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1            # [N, E], -1 ∉
    kept = (pos >= 0) & (pos < capacity)
    slot = jnp.where(kept, pos, 0)
    dispatch = kept[..., None] & (
        jax.nn.one_hot(slot, capacity, dtype=jnp.int32) > 0)  # [N, E, C]
    combine = dispatch * gate[:, None, None]
    return combine.astype(logits.dtype), dispatch


def moe_ffn(layer, x, cfg: MoEConfig):
    """x [B, T, D] → [B, T, D] through capacity-routed experts."""
    B, T, D = x.shape
    N = B * T
    E = cfg.num_experts
    capacity = max(1, int(cfg.capacity_factor * N / E))
    flat = x.reshape(N, D)
    logits = flat @ layer["router"]
    combine, dispatch = route(logits, capacity)
    # dispatch: [N, E, C] × [N, D] → expert buffers [E, C, D]
    buf = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), flat)
    h = jax.nn.silu(jnp.einsum("ecd,edi->eci", buf, layer["w_in"]))
    out = jnp.einsum("eci,eid->ecd", h, layer["w_out"])
    y = jnp.einsum("nec,ecd->nd", combine, out)
    return y.reshape(B, T, D)


def forward(params, tokens, cfg: MoEConfig, mesh: Mesh | None = None):
    del mesh
    x = params["embed"][tokens]
    for layer in params["layers"]:
        x = x + moe_ffn(layer, rms_norm(x, layer["norm"]), cfg)
    x = rms_norm(x, params["final_norm"])
    return x @ params["head"]


def loss_fn(params, tokens, cfg: MoEConfig):
    logits = forward(params, tokens[:, :-1], cfg).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, tokens[:, 1:][..., None], axis=-1)[..., 0]
    return -ll.mean()


def make_train_step(cfg: MoEConfig, mesh: Mesh | None = None,
                    lr: float = 1e-3, momentum: float = 0.9):
    del mesh  # placement comes from the param shardings

    def init_opt(params):
        return jax.tree.map(jnp.zeros_like, params)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
        new_opt = jax.tree.map(lambda m, g: momentum * m + g, opt_state, grads)
        new_params = jax.tree.map(lambda p, m: p - lr * m, params, new_opt)
        return new_params, new_opt, loss

    return init_opt, jax.jit(train_step)
