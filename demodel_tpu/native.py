"""Loader for the C++ data plane (``native/libdemodel_native.so``).

Builds on first use (``make -C native``) so a fresh checkout needs no
separate build step, then configures every ctypes prototype once — the
defaults (int restype) silently truncate 64-bit handles and offsets.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

from demodel_tpu.utils.logging import get_logger

log = get_logger("native")

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_SO = _NATIVE_DIR / "build" / "libdemodel_native.so"

#: Python mirror of the native DM_LOCK_ORDER_CHECK rank table
#: (``native/lock_order.h``) — the canonical answer to "may I call into
#: the store while holding a proxy lock" from the Python side of the
#: boundary, without parsing C++ at runtime. Low rank = outermost.
#: Kept in lockstep by the ``surface-parity`` analyzer rule: an edit to
#: either side without the other is a build-breaking finding.
NATIVE_LOCK_RANKS = {
    "kRankProxyReactor": 6,
    "kRankProxyQueue": 8,
    "kRankProxySessions": 10,
    "kRankProxyFill": 12,
    "kRankProxyLeaf": 14,
    "kRankProxyUpstream": 16,
    "kRankProxyHint": 18,
    "kRankProxyRestore": 20,
    "kRankProxyTelemetry": 22,
    "kRankProxyProfile": 24,
    "kRankProxyKtls": 26,
    "kRankProxyFdCache": 27,
    "kRankStoreGc": 30,
    "kRankStoreWriters": 32,
    "kRankStoreIndex": 34,
    "kRankStorePin": 36,
    "kRankStoreFd": 38,
    "kRankStoreHot": 40,
}

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _needs_build() -> bool:
    if not _SO.exists():
        return True
    so_mtime = _SO.stat().st_mtime
    for src in _NATIVE_DIR.glob("*.cc"):
        if src.stat().st_mtime > so_mtime:
            return True
    for hdr in _NATIVE_DIR.glob("*.h"):
        if hdr.stat().st_mtime > so_mtime:
            return True
    return False


def build() -> None:
    """(Re)build the shared library via make."""
    log.info("building native data plane (make -C %s)", _NATIVE_DIR)
    proc = subprocess.run(
        ["make", "-C", str(_NATIVE_DIR)],
        capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build failed:\n{proc.stdout}\n{proc.stderr}")


def _configure(L: ctypes.CDLL) -> None:
    c = ctypes
    P, I, I64, CP = c.c_void_p, c.c_int, c.c_int64, c.c_char_p

    def sig(name, restype, argtypes):
        fn = getattr(L, name)
        fn.restype = restype
        fn.argtypes = argtypes

    # store lifecycle + queries
    sig("dm_store_open", P, [CP, CP, I])
    sig("dm_store_close", None, [P])
    sig("dm_store_has", I, [P, CP])
    sig("dm_store_size", I64, [P, CP])
    sig("dm_store_partial_size", I64, [P, CP])
    sig("dm_store_meta", I, [P, CP, CP, I])
    sig("dm_store_pread", I64, [P, CP, P, I64, I64])
    sig("dm_store_put", I, [P, CP, P, I64, CP, CP])
    sig("dm_store_remove", I, [P, CP])
    sig("dm_store_has_digest", I, [P, CP])
    sig("dm_store_materialize", I, [P, CP, CP, CP])
    sig("dm_store_begin", P, [P, CP, I, CP, I])
    sig("dm_store_begin_ranged", P, [P, CP, I64, CP, I])
    sig("dm_store_index_json", I, [P, CP, I])
    sig("dm_store_list", I, [P, CP, I])
    sig("dm_store_gc", I64, [P, I64, c.POINTER(I64), c.POINTER(I)])
    sig("dm_store_evictions", I64, [P])
    sig("dm_store_pin", None, [P, CP])
    sig("dm_store_unpin", None, [P, CP])
    # storage-fault plane: quarantine, crash-recovery sweep, scrubber
    sig("dm_store_quarantine", I, [P, CP])
    sig("dm_store_recover", None, [P, c.c_double, c.POINTER(I), c.POINTER(I)])
    sig("dm_store_scrub", I, [P, I64, c.POINTER(I64), c.POINTER(I64),
                              c.POINTER(I)])
    sig("dm_store_storage_stats", None, [P, c.POINTER(I64)])
    sig("dm_key_for_uri", None, [CP, CP])
    # streaming writer
    sig("dm_writer_append", I, [P, P, I64])
    sig("dm_writer_offset", I64, [P])
    sig("dm_writer_digest", None, [P, CP])
    sig("dm_writer_commit", I, [P, CP])
    sig("dm_writer_abort", None, [P, I])
    # parallel range writer
    sig("dm_rw_pwrite", I, [P, P, I64, I64])
    sig("dm_rw_written", I64, [P])
    sig("dm_rw_commit", I, [P, CP, CP, CP])
    sig("dm_rw_abort", None, [P, I])
    # peer fetch (data plane in proxy.cc)
    sig("dm_peer_fetch", I64, [P, CP, I, CP, CP, CP, CP, CP, I])
    sig("dm_peer_fetch_parallel", I64,
        [P, CP, I, CP, CP, I64, I, CP, CP, CP, I])
    sig("dm_peer_fetch_into", I64, [CP, I, CP, I64, I, CP, P, CP, I])
    sig("dm_peer_fetch_window", I64, [CP, I, CP, I64, I64, I64, I, P, CP, I])
    sig("dm_upstream_fetch_parallel", I64,
        [P, CP, I, I, CP, CP, CP, I64, I, CP, CP, CP, I])
    # proxy prototypes are configured in demodel_tpu.proxy (its call sites)


def lib() -> ctypes.CDLL:
    """The loaded (building if needed) native library, prototypes set."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            # demodel: allow(no-blocking-io-under-lock) — exactly-once
            # module init: every caller NEEDS the build done before the
            # dlopen below; the lock exists to serialize precisely this
            build()
        L = ctypes.CDLL(str(_SO))
        _configure(L)
        _lib = L
        return L
