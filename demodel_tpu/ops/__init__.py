from demodel_tpu.ops import dequant
from demodel_tpu.ops.flash_attention import flash_attention
from demodel_tpu.ops.ring_attention import ring_attention

__all__ = ["dequant", "flash_attention", "ring_attention"]
