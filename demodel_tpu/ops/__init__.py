from demodel_tpu.ops import dequant
from demodel_tpu.ops.ring_attention import ring_attention

__all__ = ["dequant", "ring_attention"]
