"""On-device GGUF dequantization (pallas kernels + jnp fallback).

The HBM sink ships the *quantized* payload over the host→device link and
widens on device (SURVEY.md §2.3 "Sharded HBM placement"): for Q8_0 that is
a 3.8× link saving over shipping f32. Dispatch per format:

- **Q8_0 / Q4_0**: a pallas kernel over 256-row 2-D tiles (any block
  count — row tails are padded and sliced off) on real TPU; pure-jnp
  math off-TPU (the interpreter executes grids in Python — minutes per
  tensor).
- **K-quants (Q2_K…Q6_K)**: always the fused-jnp math path at runtime —
  the bit-unpacking layouts (12/16-byte operands, rank-1 scale vectors)
  are lane-hostile and their one-super-block kernels do not satisfy
  Mosaic's tiling rules on real TPU; XLA's fused elementwise graph is
  the right tool for this bandwidth-bound transform. The kernels remain
  as an interpret-only parity oracle under DEMODEL_FORCE_PALLAS.

Bit layouts follow the llama.cpp/ggml block spec; the numpy decoders in
:mod:`demodel_tpu.formats.gguf` (``REF_DEQUANT``) are the normative
reference these kernels are tested against (tests/test_dequant.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from demodel_tpu.formats import gguf

#: quant blocks (rows) per pallas grid step for Q4_0/Q8_0. 256 rows keeps
#: every operand Mosaic-tileable: sublane tiling is 8 (f32 scales), 16
#: (bf16 out) and 32 (int8 payload), and 256 is a multiple of all three —
#: the old rank-1 (8,)-row blocks failed Mosaic's rank-1 tiling check on
#: the first real-chip compile (round 5)
Q_TILE = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _force_pallas() -> bool:
    """DEMODEL_FORCE_PALLAS=1 pins the pallas path regardless of backend
    (the kernel parity tests set it; interpret mode executes the grid in
    Python)."""
    import os

    return os.environ.get("DEMODEL_FORCE_PALLAS", "").strip() == "1"


def _use_pallas() -> bool:
    """Pallas on the real chip; vectorized jnp elsewhere. The interpreter
    executes the grid step-by-step in Python — measured 267 s for ONE
    8M-element Q8_0 tensor on this host, vs <1 s for the identical
    `_math` jnp — so off-TPU delivery takes the math path and the kernels
    stay covered by the dedicated kernel tests."""
    return _force_pallas() or jax.default_backend() == "tpu"


# --------------------------------------------------------------- Q8_0/Q4_0


def _q8_0_math(d, qs, out_dtype):
    return (d.astype(jnp.float32)[:, None]
            * qs.astype(jnp.float32)).astype(out_dtype)


def _q8_0_kernel(d_ref, qs_ref, o_ref, *, out_dtype):
    # d block is (R, 1) f32 — broadcasts across the 32 lane columns
    o_ref[...] = (d_ref[...] * qs_ref[...].astype(jnp.float32)).astype(
        out_dtype)


def _pad_rows(x, nbp: int):
    nb = x.shape[0]
    if nbp == nb:
        return jnp.asarray(x)
    widths = [(0, nbp - nb)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(jnp.asarray(x), widths)


def dequant_q8_0(d, qs, out_dtype=jnp.bfloat16):
    """d: (nb,) f16, qs: (nb, 32) i8 → flat (nb*32,) out_dtype."""
    nb = d.shape[0]
    if nb == 0 or not _use_pallas():
        return _q8_0_math(jnp.asarray(d), jnp.asarray(qs), out_dtype).reshape(-1)
    nbp = -(-nb // Q_TILE) * Q_TILE  # pad the row tail; sliced off below
    dp = _pad_rows(jnp.asarray(d).astype(jnp.float32), nbp).reshape(nbp, 1)
    qsp = _pad_rows(qs, nbp)
    try:
        out = pl.pallas_call(
            functools.partial(_q8_0_kernel, out_dtype=out_dtype),
            grid=(nbp // Q_TILE,),
            in_specs=[pl.BlockSpec((Q_TILE, 1), lambda i: (i, 0)),
                      pl.BlockSpec((Q_TILE, gguf.QK), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((Q_TILE, gguf.QK), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((nbp, gguf.QK), out_dtype),
            interpret=_interpret(),
        )(dp, qsp)
    except Exception:  # noqa: BLE001 — Mosaic compile errors vary by version
        # a Mosaic tiling rejection on some chip generation must degrade
        # to the (slower, correct) jnp math, not fail the whole delivery;
        # the parity oracle pins the kernel, so surface the error there
        if _force_pallas():
            raise
        return _q8_0_math(jnp.asarray(d), jnp.asarray(qs),
                          out_dtype).reshape(-1)
    return out.reshape(-1)[:nb * gguf.QK]


def _q4_0_math(d, qs, out_dtype):
    qs = qs.astype(jnp.int32)
    lo = (qs & 0xF) - 8
    hi = (qs >> 4) - 8
    q = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
    return (d.astype(jnp.float32)[:, None] * q).astype(out_dtype)


def _q4_0_kernel(d_ref, qs_ref, o_ref, *, out_dtype):
    qs = qs_ref[...].astype(jnp.int32)
    lo = (qs & 0xF) - 8
    hi = (qs >> 4) - 8
    q = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
    o_ref[...] = (d_ref[...] * q).astype(out_dtype)


def dequant_q4_0(d, qs, out_dtype=jnp.bfloat16):
    """d: (nb,) f16, qs: (nb, 16) u8 → flat (nb*32,) out_dtype."""
    nb = d.shape[0]
    if nb == 0 or not _use_pallas():
        return _q4_0_math(jnp.asarray(d), jnp.asarray(qs), out_dtype).reshape(-1)
    nbp = -(-nb // Q_TILE) * Q_TILE
    dp = _pad_rows(jnp.asarray(d).astype(jnp.float32), nbp).reshape(nbp, 1)
    qsp = _pad_rows(qs, nbp)
    try:
        out = pl.pallas_call(
            functools.partial(_q4_0_kernel, out_dtype=out_dtype),
            grid=(nbp // Q_TILE,),
            in_specs=[pl.BlockSpec((Q_TILE, 1), lambda i: (i, 0)),
                      pl.BlockSpec((Q_TILE, gguf.QK // 2), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((Q_TILE, gguf.QK), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((nbp, gguf.QK), out_dtype),
            interpret=_interpret(),
        )(dp, qsp)
    except Exception:  # noqa: BLE001 — Mosaic compile errors vary by version
        # same degrade-not-crash stance as dequant_q8_0 above
        if _force_pallas():
            raise
        return _q4_0_math(jnp.asarray(d), jnp.asarray(qs),
                          out_dtype).reshape(-1)
    return out.reshape(-1)[:nb * gguf.QK]


# ----------------------------------------------------------------- K-quants
#
# One pallas kernel per format, gridded one super-block (256 elems) per
# step; the shared jnp math mirrors formats.gguf's numpy reference loops
# vectorized over the block axis.


def _q2_k_math(d, dmin, scales, qs, out_dtype):
    nb = d.shape[0]
    df = d.astype(jnp.float32)
    mf = dmin.astype(jnp.float32)
    scales = scales.astype(jnp.int32)
    qs = qs.astype(jnp.int32)
    cols = []
    for half in range(2):
        q = qs[:, half * 32:(half + 1) * 32]
        for j in range(4):
            grp = (q >> (2 * j)) & 3
            for sub in range(2):
                is_ = half * 8 + 2 * j + sub
                sc = scales[:, is_]
                dl = df * (sc & 0xF).astype(jnp.float32)
                ml = mf * (sc >> 4).astype(jnp.float32)
                seg = grp[:, sub * 16:(sub + 1) * 16].astype(jnp.float32)
                cols.append(dl[:, None] * seg - ml[:, None])
    # cols are in y-order by construction: (half, j, sub)
    return jnp.concatenate(cols, axis=1).reshape(nb, 256).astype(out_dtype)


def _q3_k_scales(scales):
    """jnp port of formats.gguf.unpack_q3k_scales (12B → 16 6-bit - 32)."""
    s = scales.astype(jnp.uint32)

    def dword(i):
        return (s[:, 4 * i] | (s[:, 4 * i + 1] << 8) | (s[:, 4 * i + 2] << 16)
                | (s[:, 4 * i + 3] << 24))

    raw0, raw1, tmp = dword(0), dword(1), dword(2)
    kmask1, kmask2 = 0x03030303, 0x0F0F0F0F
    aux0 = (raw0 & kmask2) | (((tmp >> 0) & kmask1) << 4)
    aux1 = (raw1 & kmask2) | (((tmp >> 2) & kmask1) << 4)
    aux2 = ((raw0 >> 4) & kmask2) | (((tmp >> 4) & kmask1) << 4)
    aux3 = ((raw1 >> 4) & kmask2) | (((tmp >> 6) & kmask1) << 4)
    bytes_ = []
    for aux in (aux0, aux1, aux2, aux3):
        for shift in (0, 8, 16, 24):
            bytes_.append((aux >> shift) & 0xFF)
    sc = jnp.stack(bytes_, axis=1).astype(jnp.int32)
    sc = jnp.where(sc >= 128, sc - 256, sc)  # int8 reinterpret
    return sc - 32


def _q3_k_math(d, scales, hmask, qs, out_dtype):
    nb = d.shape[0]
    df = d.astype(jnp.float32)
    sc = _q3_k_scales(scales)
    hmask = hmask.astype(jnp.int32)
    qs = qs.astype(jnp.int32)
    cols = []
    for half in range(2):
        q = qs[:, half * 32:(half + 1) * 32]
        for j in range(4):
            grp_i = half * 4 + j
            low = (q >> (2 * j)) & 3
            hbit = (hmask >> grp_i) & 1
            qv = low - jnp.where(hbit != 0, 0, 4)
            for sub in range(2):
                is_ = half * 8 + 2 * j + sub
                dl = df * sc[:, is_].astype(jnp.float32)
                seg = qv[:, sub * 16:(sub + 1) * 16].astype(jnp.float32)
                cols.append(dl[:, None] * seg)
    return jnp.concatenate(cols, axis=1).reshape(nb, 256).astype(out_dtype)


def _k4_scales(scales):
    """jnp port of unpack_k4_scales: (nb,12) u8 → (sc, m) each (nb,8)."""
    q = scales.astype(jnp.int32)
    sc, m = [], []
    for j in range(8):
        if j < 4:
            sc.append(q[:, j] & 63)
            m.append(q[:, j + 4] & 63)
        else:
            sc.append((q[:, j + 4] & 0xF) | (((q[:, j - 4] >> 6) & 3) << 4))
            m.append((q[:, j + 4] >> 4) | (((q[:, j] >> 6) & 3) << 4))
    return jnp.stack(sc, axis=1), jnp.stack(m, axis=1)


def _q4_k_math(d, dmin, scales, qs, out_dtype):
    nb = d.shape[0]
    df = d.astype(jnp.float32)
    mf = dmin.astype(jnp.float32)
    sc, mn = _k4_scales(scales)
    qs = qs.astype(jnp.int32)
    cols = []
    for j in range(4):
        q = qs[:, 32 * j:32 * (j + 1)]
        d1 = df * sc[:, 2 * j].astype(jnp.float32)
        m1 = mf * mn[:, 2 * j].astype(jnp.float32)
        d2 = df * sc[:, 2 * j + 1].astype(jnp.float32)
        m2 = mf * mn[:, 2 * j + 1].astype(jnp.float32)
        cols.append(d1[:, None] * (q & 0xF).astype(jnp.float32) - m1[:, None])
        cols.append(d2[:, None] * (q >> 4).astype(jnp.float32) - m2[:, None])
    return jnp.concatenate(cols, axis=1).reshape(nb, 256).astype(out_dtype)


def _q5_k_math(d, dmin, scales, qh, qs, out_dtype):
    nb = d.shape[0]
    df = d.astype(jnp.float32)
    mf = dmin.astype(jnp.float32)
    sc, mn = _k4_scales(scales)
    qh = qh.astype(jnp.int32)
    qs = qs.astype(jnp.int32)
    cols = []
    for j in range(4):
        q = qs[:, 32 * j:32 * (j + 1)]
        h1 = (qh >> (2 * j)) & 1
        h2 = (qh >> (2 * j + 1)) & 1
        q1 = (q & 0xF) + (h1 << 4)
        q2 = (q >> 4) + (h2 << 4)
        d1 = df * sc[:, 2 * j].astype(jnp.float32)
        m1 = mf * mn[:, 2 * j].astype(jnp.float32)
        d2 = df * sc[:, 2 * j + 1].astype(jnp.float32)
        m2 = mf * mn[:, 2 * j + 1].astype(jnp.float32)
        cols.append(d1[:, None] * q1.astype(jnp.float32) - m1[:, None])
        cols.append(d2[:, None] * q2.astype(jnp.float32) - m2[:, None])
    return jnp.concatenate(cols, axis=1).reshape(nb, 256).astype(out_dtype)


def _q6_k_math(d, sc, ql, qh, out_dtype):
    nb = d.shape[0]
    df = d.astype(jnp.float32)
    scf = sc.astype(jnp.float32)
    ql = ql.astype(jnp.int32)
    qh = qh.astype(jnp.int32)
    cols = []
    for half in range(2):
        l1 = ql[:, half * 64:half * 64 + 32]
        l2 = ql[:, half * 64 + 32:half * 64 + 64]
        h = qh[:, half * 32:half * 32 + 32]
        q1 = ((l1 & 0xF) | (((h >> 0) & 3) << 4)) - 32
        q2 = ((l2 & 0xF) | (((h >> 2) & 3) << 4)) - 32
        q3 = ((l1 >> 4) | (((h >> 4) & 3) << 4)) - 32
        q4 = ((l2 >> 4) | (((h >> 6) & 3) << 4)) - 32
        for qv, col in ((q1, 0), (q2, 32), (q3, 64), (q4, 96)):
            for subi in range(2):
                is_ = half * 8 + col // 16 + subi
                dl = df * scf[:, is_]
                seg = qv[:, subi * 16:(subi + 1) * 16].astype(jnp.float32)
                cols.append(dl[:, None] * seg)
    return jnp.concatenate(cols, axis=1).reshape(nb, 256).astype(out_dtype)


def _k_quant_call(math_fn, parts, out_dtype, part_widths):
    """Run a K-quant math fn as a pallas kernel, one super-block per grid
    step, or as plain fused jnp.

    On REAL TPU the math path is used: K-quant bit-unpacking is
    lane-hostile (1-wide sublane blocks, 12/16-byte operands, rank-1
    scale vectors) and the one-super-block-per-step kernel layout does
    not satisfy Mosaic's tiling rules — the fused XLA elementwise graph
    is the right tool for this bandwidth-bound transform. The kernels
    remain exercised under DEMODEL_FORCE_PALLAS (interpret-mode kernel
    tests), keeping the math/kernel parity oracle alive."""
    nb = parts[0].shape[0]
    if nb == 0:
        return jnp.zeros((0,), out_dtype)
    if not _force_pallas():
        return math_fn(*parts, out_dtype).reshape(-1)

    def kernel(*refs):
        ins, o_ref = refs[:-1], refs[-1]
        o_ref[...] = math_fn(*[r[...] for r in ins], out_dtype)

    in_specs = []
    for p, w in zip(parts, part_widths):
        if w is None:
            in_specs.append(pl.BlockSpec((1,), lambda i: (i,)))
        else:
            in_specs.append(pl.BlockSpec((1, w), lambda i: (i, 0)))
    out = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gguf.QK_K), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, gguf.QK_K), out_dtype),
        # ALWAYS interpreted: this one-super-block layout is exactly
        # what Mosaic rejects on real TPU (round-5 on-chip compile), so
        # a forced run on a TPU host must not hand it to the compiler
        interpret=True,
    )(*parts)
    return out.reshape(-1)


def dequant_q2_k(d, dmin, scales, qs, out_dtype=jnp.bfloat16):
    return _k_quant_call(_q2_k_math, (d, dmin, scales, qs), out_dtype,
                         (None, None, 16, 64))


def dequant_q3_k(d, scales, hmask, qs, out_dtype=jnp.bfloat16):
    return _k_quant_call(_q3_k_math, (d, scales, hmask, qs), out_dtype,
                         (None, 12, 32, 64))


def dequant_q4_k(d, dmin, scales, qs, out_dtype=jnp.bfloat16):
    return _k_quant_call(_q4_k_math, (d, dmin, scales, qs), out_dtype,
                         (None, None, 12, 128))


def dequant_q5_k(d, dmin, scales, qh, qs, out_dtype=jnp.bfloat16):
    return _k_quant_call(_q5_k_math, (d, dmin, scales, qh, qs), out_dtype,
                         (None, None, 12, 32, 128))


def dequant_q6_k(d, sc, ql, qh, out_dtype=jnp.bfloat16):
    return _k_quant_call(_q6_k_math, (d, sc, ql, qh), out_dtype,
                         (None, 16, 128, 64))


# ------------------------------------------------------------- whole tensor

_FNS = {
    gguf.GGML_Q8_0: dequant_q8_0,
    gguf.GGML_Q4_0: dequant_q4_0,
    gguf.GGML_Q2_K: dequant_q2_k,
    gguf.GGML_Q3_K: dequant_q3_k,
    gguf.GGML_Q4_K: dequant_q4_k,
    gguf.GGML_Q5_K: dequant_q5_k,
    gguf.GGML_Q6_K: dequant_q6_k,
}


def dequant_gguf_tensor(t: gguf.GGUFTensor, decoded,
                        out_dtype=jnp.bfloat16) -> jax.Array:
    """Whole-tensor dequant (the sink's non-shardwise fallback path)."""
    if t.ggml_type in (gguf.GGML_F32, gguf.GGML_F16):
        return jnp.asarray(np.asarray(decoded)).astype(out_dtype)
    fn = _FNS[t.ggml_type]
    flat = fn(*[jnp.asarray(p) for p in decoded], out_dtype)
    return flat.reshape(t.shape)
