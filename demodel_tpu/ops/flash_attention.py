"""Fused flash attention — pallas TPU kernel for the model hot path.

The einsum attention in :mod:`demodel_tpu.models.llama` materializes the
(B, H, S, S) score tensor in HBM; at long sequence that tensor IS the
memory bill (32k² × heads ≫ the weights). This kernel streams K/V blocks
through VMEM against resident Q blocks with the online-softmax
accumulator, so HBM traffic is O(S·D) per head and the MXU sees big
(block_q × D) × (D × block_k) matmuls:

- grid ``(B, H, Sq/block_q, Sk/block_k)`` — the K dimension iterates
  minor-most, which on TPU is sequential per core, so the fp32
  accumulators (m, l, acc) live in VMEM scratch across K steps;
- GQA folded into the BlockSpec index map (`kv_head = h // q_per_kv`) —
  no materialized head repeat (for a KV cache this is the decode-time
  memory bill);
- two DYNAMIC scalars ride in SMEM: ``kv_len`` (valid key prefix — K
  blocks past it are skipped, so decode over a mostly-empty cache costs
  only the filled prefix) and ``causal_offset`` (which key the last
  query aligns to — decode windows, and the shifted diagonals of ring
  attention steps);
- causal blocks above the diagonal are skipped too, halving prefill;
- lengths that don't divide the blocks are zero-padded and masked;
- the per-row log-sum-exp is emitted alongside the output, which is
  exactly what :mod:`demodel_tpu.ops.ring_attention` needs to combine
  per-ring-step partials without ever holding raw score tensors.

Backward: ``jax.custom_vjp`` recomputes the reference attention for
gradients (flash-speed forward, standard-memory backward) — training
still differentiates end-to-end, and inference/serving (the delivery
framework's consumer) pays no backward at all.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------- reference


def _mask(Sq, Sk, kv_len, causal, causal_offset):
    ki = jnp.arange(Sk)[None, :]
    qi = jnp.arange(Sq)[:, None]
    m = ki < kv_len
    if causal:
        m = m & (ki <= qi + causal_offset)
    return m


def reference_attention_lse(q, k, v, causal: bool = True, scale=None,
                            kv_len=None, causal_offset=None):
    """Einsum attention (GQA-aware) returning ``(out, lse)`` — the
    numerics oracle and the recompute backward. q: (B, Sq, H, D);
    k/v: (B, Sk, G, D), G | H. ``kv_len`` bounds the valid key prefix;
    ``causal_offset`` shifts the diagonal (default aligns the LAST query
    with key ``kv_len - 1``)."""
    B, Sq, H, D = q.shape
    Sk, G = k.shape[1], k.shape[2]
    if G != H:
        rep = H // G
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if scale is None:
        scale = D ** -0.5
    if kv_len is None:
        kv_len = Sk
    kv_len = jnp.asarray(kv_len, jnp.int32)
    if causal_offset is None:
        causal_offset = kv_len - Sq
    causal_offset = jnp.asarray(causal_offset, jnp.int32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if kv_len.ndim == 0 and causal_offset.ndim == 0:
        mask = _mask(Sq, Sk, kv_len, causal, causal_offset)[None, None]
    else:
        # per-batch validity (ragged batched decode): broadcast to (B,1,Sq,Sk)
        kvb = jnp.broadcast_to(kv_len, (B,))[:, None, None, None]
        offb = jnp.broadcast_to(causal_offset, (B,))[:, None, None, None]
        ki = jnp.arange(Sk)[None, None, None, :]
        qi = jnp.arange(Sq)[None, None, :, None]
        mask = ki < kvb
        if causal:
            mask = mask & (ki <= qi + offb)
    scores = jnp.where(mask, scores, NEG_INF)
    lse = jax.scipy.special.logsumexp(scores, axis=-1)  # (B, H, Sq)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)
    return out, lse.transpose(0, 2, 1)  # lse → (B, Sq, H)


def reference_attention(q, k, v, causal: bool = True, scale=None,
                        kv_len=None, causal_offset=None):
    return reference_attention_lse(q, k, v, causal, scale, kv_len,
                                   causal_offset)[0]


# ----------------------------------------------------------------- kernel


#: TPU lane width — the m/l running stats live lane-replicated at this
#: width (the layout Mosaic lowers without relayout ops; matching the
#: convention of jax's own pallas TPU flash kernel, which this kernel's
#: earlier (B,S,H,D)-blocked layout violated: a block of 1 over the
#: 8-wide H dim sat in the sublane slot and failed Mosaic's tiling check
#: on real silicon — first on-chip compile, round 5)
_LANES = 128
#: lane width of the lse HBM buffer — the kernel's (block_q, 128) stats
#: are lane-sliced to this on the store; consumers read lane 0. Kept > 1
#: only so the store stays a plain slice (no cross-lane reduce)
_LSE_LANES = 8


def _lanes(x, n: int):
    """(block_q, 128) lane-replicated stat → (block_q, n) for combining
    with an n-lane tile (n ≤ 128 slices; multiples of 128 tile; other
    widths — e.g. D=192 heads — broadcast from one lane)."""
    if n <= _LANES:
        return x[:, :n]
    reps, rem = divmod(n, _LANES)
    if rem == 0:
        return jnp.tile(x, (1, reps)) if reps > 1 else x
    return jnp.broadcast_to(x[:, :1], (x.shape[0], n))


def _flash_kernel(scalars_ref, q_ref, k_ref, v_ref, o_ref, *rest,
                  scale, causal, block_q, block_k, with_lse):
    """One (b, h, qi, ki) step over (B, H, S, D)-laid-out tiles. Scratch
    (acc, m, l) persists across the minor-most ki dimension; init at
    ki==0, finalize at the last ki. m/l are (block_q, 128) with the stat
    replicated across lanes. The lse output (and its 128-lane HBM
    buffer) exists only when requested — the plain forward path skips
    it entirely."""
    if with_lse:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        lse_ref = None
        acc_ref, m_ref, l_ref = rest
    ki = pl.program_id(3)
    qi = pl.program_id(2)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    b = pl.program_id(0)
    sk_actual = scalars_ref[2 + 2 * b]       # per-batch valid key prefix
    offset = scalars_ref[2 + 2 * b + 1]      # per-batch diagonal shift
    # skip K blocks that are entirely invalid (past kv_len) or entirely
    # above the causal diagonal — decode over a long, mostly-empty cache
    # then costs only the filled prefix
    live = ki * block_k < sk_actual
    if causal:
        live &= ki * block_k < (qi + 1) * block_q + offset

    @pl.when(live)
    def _step():
        q = q_ref[0, 0, :, :].astype(jnp.float32) * scale
        k = k_ref[0, 0, :, :].astype(jnp.float32)
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (block_q, block_k), MXU

        q_idx = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_idx = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_idx < sk_actual  # padded / unfilled keys never score
        if causal:
            mask &= k_idx <= q_idx + offset
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...]                       # (block_q, 128)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1)[:, None])
        alpha = jnp.exp(m_prev - m_new)           # (block_q, 128)
        # zero masked entries explicitly: a row with NO visible key in a
        # live block has every s == NEG_INF, so m_new == NEG_INF and
        # exp(s - m_new) == 1 for all entries — without this, l would
        # accumulate block_k and the finalize's l==0 guard never fires
        # (the output would silently become mean(V) instead of zeros)
        p = jnp.where(mask, jnp.exp(s - _lanes(m_new, block_k)), 0.0)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1)[:, None]
        D = acc_ref.shape[-1]
        acc_ref[...] = acc_ref[...] * _lanes(alpha, D) + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        # fully-masked rows (past-Sq padding / no visible keys) have
        # l == 0 — emit zeros and an lse of NEG_INF (combines as "no
        # contribution" in the ring's log-space merge)
        l = l_ref[...]                            # (block_q, 128)
        safe = jnp.where(l == 0.0, 1.0, l)
        D = acc_ref.shape[-1]
        o_ref[0, 0, :, :] = (acc_ref[...] * _lanes(1.0 / safe, D)).astype(
            o_ref.dtype)
        if with_lse:
            lse_ref[0, 0, :, :] = jnp.where(
                l > 0.0, m_ref[...] + jnp.log(safe),
                NEG_INF)[:, :_LSE_LANES]


def _pad_to(x, axis: int, multiple: int):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q, k, v, kv_len, causal_offset, causal, scale, block_q,
                   block_k, with_lse=True):
    B, Sq, H, D = q.shape
    Sk, G = k.shape[1], k.shape[2]
    if H % G != 0:
        raise ValueError(f"q heads {H} not a multiple of kv heads {G}")
    q_per_kv = H // G
    if scale is None:
        scale = D ** -0.5
    block_q = min(block_q, max(Sq, 1))
    block_k = min(block_k, max(Sk, 1))
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    nq, nk = qp.shape[1] // block_q, kp.shape[1] // block_k
    if kv_len is None:
        kv_len = Sk
    kv_len = jnp.asarray(kv_len, jnp.int32)
    if causal_offset is None:
        causal_offset = kv_len - Sq
    causal_offset = jnp.asarray(causal_offset, jnp.int32)
    # SMEM scalar layout: 2 reserved slots, then per-batch
    # [kv_len, causal_offset] pairs (scalars broadcast across the batch;
    # vectors give ragged batched decode its per-example windows)
    kvb = jnp.broadcast_to(kv_len, (B,))
    offb = jnp.broadcast_to(causal_offset, (B,))
    scalars = jnp.concatenate([
        jnp.zeros((2,), jnp.int32),
        jnp.stack([kvb, offb], axis=1).reshape(-1),
    ])

    # kernel layout is (B, H, S, D): heads become a pure grid dimension
    # and the last two block dims (seq block, D) are the MXU-tiled pair —
    # the layout Mosaic accepts (H in the sublane slot is rejected on
    # real TPU). The transposes are HBM copies XLA fuses with adjacent
    # ops; the einsum path's S² score tensor still dwarfs them.
    qt = qp.transpose(0, 2, 1, 3)
    kt = kp.transpose(0, 2, 1, 3)
    vt = vp.transpose(0, 2, 1, 3)
    Sqp = qp.shape[1]
    out_specs = [
        pl.BlockSpec((1, 1, block_q, D),
                     lambda b, h, qi, ki: (b, h, qi, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct((B, H, Sqp, D), q.dtype)]
    if with_lse:
        # lse rides lane-replicated at width _LSE_LANES (8): the minor
        # block dim spans the full array dim, which Mosaic accepts at
        # any size — 16× leaner than mirroring the kernel's 128-lane
        # stats into HBM (the jax reference kernel's choice), and the
        # store is a cheap lane-slice of those stats. Only allocated
        # when a caller (the ring merge) actually consumes it — the
        # plain forward must not pay it at all.
        out_specs.append(pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                                      lambda b, h, qi, ki: (b, h, qi, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((B, H, Sqp, _LSE_LANES), jnp.float32))
    res = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, with_lse=with_lse),
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // q_per_kv, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h // q_per_kv, ki, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),       # acc
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom l
        ],
        interpret=_interpret(),
    )(scalars, qt, kt, vt)
    if with_lse:
        out, lse = res
        return (out[:, :, :Sq].transpose(0, 2, 1, 3),
                lse[:, :, :Sq, 0].transpose(0, 2, 1))
    return res[0][:, :, :Sq].transpose(0, 2, 1, 3), None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_core(q, k, v, kv_len, causal_offset, causal, scale, block_q,
                block_k, with_lse):
    return _flash_forward(q, k, v, kv_len, causal_offset, causal, scale,
                          block_q, block_k, with_lse)


def _fwd(q, k, v, kv_len, causal_offset, causal, scale, block_q, block_k,
         with_lse):
    out = _flash_forward(q, k, v, kv_len, causal_offset, causal, scale,
                         block_q, block_k, with_lse)
    return out, (q, k, v, kv_len, causal_offset)


def _bwd(causal, scale, block_q, block_k, with_lse, res, g):
    q, k, v, kv_len, causal_offset = res
    if with_lse:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: reference_attention_lse(
                q_, k_, v_, causal, scale, kv_len=kv_len,
                causal_offset=causal_offset),
            q, k, v)
    else:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: (reference_attention(
                q_, k_, v_, causal, scale, kv_len=kv_len,
                causal_offset=causal_offset), None),
            q, k, v)
    dq, dk, dv = vjp(g)

    def _zero_int(x):
        return None if x is None else \
            np.zeros(jnp.shape(jnp.asarray(x)), jax.dtypes.float0)

    return dq, dk, dv, _zero_int(kv_len), _zero_int(causal_offset)


_flash_core.defvjp(_fwd, _bwd)


def flash_attention(q, k, v, kv_len=None, causal: bool = True, scale=None,
                    block_q: int = 128, block_k: int = 128,
                    causal_offset=None, return_lse: bool = False):
    """Fused attention. q: (B, Sq, H, D); k/v: (B, Sk, G, D) with G | H
    (GQA). Returns (B, Sq, H, D) in q's dtype (plus the per-row
    log-sum-exp, (B, Sq, H) f32, when ``return_lse``). ``kv_len``
    (static or traced) bounds the valid key prefix — pass the filled
    cache length for decode. ``causal_offset`` shifts the diagonal
    (query i sees keys ≤ i+offset); it defaults to ``kv_len - Sq``,
    aligning the LAST query with the last valid key."""
    out, lse = _flash_core(q, k, v, kv_len, causal_offset, causal, scale,
                           block_q, block_k, return_lse)
    return (out, lse) if return_lse else out
