"""Default policy for the fused pallas attention kernels (VERDICT r4 #2).

The kernels are parity/grad-tested in interpret mode on CPU, but SMEM
scalar prefetch, ``@pl.when``-persistent scratch, and GQA index maps are
exactly the constructs that lower differently (or fail) under Mosaic on
real TPU. The defaults therefore flip on only when BOTH hold:

- the process is actually running on a TPU backend, and
- an on-chip validation record exists — written by
  ``tools/on_recovery.py`` after a green compile+parity run on real
  silicon and committed next to this module, so a validated build ships
  flash-on for every user.

Explicit env settings always win, in both directions:
``DEMODEL_FLASH_ATTN=1`` forces the kernel anywhere (interpret mode off
TPU), ``DEMODEL_FLASH_ATTN=0`` forces the einsum path even on validated
silicon. Same contract for ``DEMODEL_FLASH_RING``.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

#: committed by tools/on_recovery.py after an on-chip parity pass
ONCHIP_RECORD = Path(__file__).parent / "_flash_onchip_validated.json"

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


def _env_flag(name: str) -> bool | None:
    """Tri-state env read: True / False when set either way, None when
    unset (policy decides)."""
    raw = os.environ.get(name, "").strip().lower()
    if raw in _TRUE:
        return True
    if raw in _FALSE:
        return False
    return None


def _load_record() -> dict:
    """The committed on-chip record, or {} when absent/unreadable."""
    try:
        return json.loads(ONCHIP_RECORD.read_text())
    except (OSError, ValueError):
        return {}


def flash_validated_on_chip() -> bool:
    """True when a committed on-chip parity record says the kernels
    compiled under Mosaic and matched the einsum oracle on real TPU."""
    return bool(_load_record().get("ok"))


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def _default_on() -> bool:
    return _on_tpu() and flash_validated_on_chip()


def use_flash_attention() -> bool:
    """Should model attention route through the fused pallas kernel?"""
    env = _env_flag("DEMODEL_FLASH_ATTN")
    if env is not None:
        return env
    return _default_on()


def _ring_validated_on_chip() -> bool:
    """The ring path compiles the flash kernel INSIDE shard_map (per-step
    tiles + log-space merge) — a distinct lowering from the plain
    forward, validated separately. Older records without the field fall
    back to the overall ok (pre-split behavior)."""
    rec = _load_record()
    return bool(rec.get("ring_ok", rec.get("ok")))


def use_flash_ring() -> bool:
    """Should ring attention compute each step with the fused kernel?"""
    env = _env_flag("DEMODEL_FLASH_RING")
    if env is not None:
        return env
    return _on_tpu() and _ring_validated_on_chip()
