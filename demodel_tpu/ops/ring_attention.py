"""Ring attention: exact context-parallel attention over an ``sp`` mesh axis.

Long-context delivery-side parallelism (SURVEY.md §5 "Long-context /
sequence parallelism"): the sequence is sharded over ``sp``; K/V chunks
rotate around the ring via ``lax.ppermute`` while each device keeps a
numerically-stable online-softmax accumulator (flash-attention style), so
attention is EXACT — identical to dense up to float error — with activation
memory O(T/n) per device and N-1 ICI hops instead of an all-gather.

Supports causal masking (global positions derived from the ring index),
grouped-query attention (fewer K/V heads than Q heads), and sequences that
do not divide the ring size (internal padding, masked out of the softmax).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30  # large-but-finite: -inf rows would NaN through exp/where


def dense_attention(q, k, v, causal: bool = True,
                    scale: float | None = None) -> jax.Array:
    """Reference single-device attention. q: [B,T,H,D], k/v: [B,T,Hkv,D]."""
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    if H != Hkv:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if scale is None:
        scale = D ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _use_flash_ring() -> bool:
    """Compute each ring step with the fused pallas kernel
    (ops/flash_attention.py), combining per-step partials in log space —
    no (B,H,Tq,Tk) score tensor per step, and no GQA head repeat riding
    the ppermute? DEMODEL_FLASH_RING forces either way; unset, defaults
    ON on validated TPU silicon (ops/flash_default.py)."""
    from demodel_tpu.ops.flash_default import use_flash_ring as _p

    return _p()


def _ring_attention_flash(q, k, v, axis_name, causal, scale, kv_len):
    """Flash-tiled ring: per step, the kernel returns the NORMALIZED
    partial and its per-row logsumexp; partials merge as
    ``O ← O·e^{L−L'} + O_i·e^{L_i−L'}`` with ``L' = logaddexp(L, L_i)``
    — numerically the same online softmax the einsum path runs, held at
    row granularity instead of materialized scores."""
    from demodel_tpu.ops.flash_attention import flash_attention

    B, Tq, H, D = q.shape
    if scale is None:
        scale = D ** -0.5
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    Tk = k.shape[1]

    O = jnp.zeros((B, Tq, H, D), jnp.float32)
    L = jnp.full((B, Tq, H), NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        src = (my - step) % n
        # absolute-position masking folded into the kernel's scalars:
        # query i (global my·Tq+i) sees key j (global src·Tk+j) iff
        # j ≤ i + (my·Tq − src·Tk); ring padding is key-validity
        offset_step = my * Tq - src * Tk
        kv_local = Tk if kv_len is None else jnp.clip(
            kv_len - src * Tk, 0, Tk)
        out_i, lse_i = flash_attention(
            q, k, v, kv_len=kv_local, causal=causal, scale=scale,
            causal_offset=offset_step, return_lse=True)
        L_comb = jnp.logaddexp(L, lse_i)
        O = (O * jnp.exp(L - L_comb)[..., None]
             + out_i.astype(jnp.float32) * jnp.exp(lse_i - L_comb)[..., None])
        L = L_comb
        if step != n - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
    return O.astype(q.dtype)


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                   scale: float | None = None,
                   kv_len: jax.Array | None = None,
                   use_flash: bool | None = None) -> jax.Array:
    """Per-shard ring attention (call inside shard_map over ``axis_name``).

    q: [B, T_loc, H, D]; k/v: [B, T_loc, Hkv, D] (GQA repeats on the fly).
    ``kv_len`` (global) masks ring padding when the true sequence length is
    not a multiple of the ring size.
    """
    if use_flash is None:
        use_flash = _use_flash_ring()
    if use_flash:
        return _ring_attention_flash(q, k, v, axis_name, causal, scale,
                                     kv_len)
    B, Tq, H, D = q.shape
    Hkv = k.shape[2]
    if H != Hkv:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if scale is None:
        scale = D ** -0.5
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    Tk = k.shape[1]

    q32 = q.astype(jnp.float32) * scale
    num = jnp.zeros((B, H, Tq, D), jnp.float32)
    den = jnp.zeros((B, H, Tq), jnp.float32)
    m = jnp.full((B, H, Tq), NEG_INF, jnp.float32)

    q_pos = my * Tq + jnp.arange(Tq)

    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        src = (my - step) % n
        k_pos = src * Tk + jnp.arange(Tk)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k.astype(jnp.float32))
        valid = jnp.ones((Tq, Tk), bool)
        if causal:
            valid &= q_pos[:, None] >= k_pos[None, :]
        if kv_len is not None:
            valid &= (k_pos < kv_len)[None, :]
        scores = jnp.where(valid[None, None], scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        # masked entries are zeroed EXPLICITLY, not via underflow: a row
        # with zero visible keys this step has m_new == NEG_INF, so
        # exp(scores - m_new) would be 1 (not 0) for every masked entry
        # and den would silently accumulate Tk (output = mean of V)
        p = jnp.where(valid[None, None],
                      jnp.exp(scores - m_new[..., None]), 0.0)
        num = num * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
        den = den * alpha + p.sum(axis=-1)
        m = m_new
        if step != n - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    out = num / jnp.maximum(den, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis: str = "sp",
                           causal: bool = True) -> jax.Array:
    """Global-view wrapper: shards the sequence over ``axis`` (padding to a
    multiple of the ring size, masked), runs the ring, unpads.

    Eager calls (concrete arrays — serving / tests, not under an outer
    ``jit``) run under a ``compute.ring-attention`` span when EXPORT
    tracing is opted in (``DEMODEL_TRACE`` / ``trace.enable()``), so the
    compute plane shows up in the critical-path report and the stage
    histograms alongside pull/serve/restore. The span syncs the result
    (a dispatch-only duration would be a lie), so it deliberately does
    NOT run under the default observe tier — default-config callers keep
    fully async dispatch. ``jit``-traced calls skip the span entirely (a
    span inside ``jit`` would record trace-time once, not run time)."""
    n = int(mesh.shape[axis])
    B, T, H, D = q.shape

    def run() -> jax.Array:
        nonlocal q, k, v
        pad = (-T) % n
        kv_len = None
        if pad:
            kv_len = jnp.int32(T)
            zq = ((0, 0), (0, pad), (0, 0), (0, 0))
            q = jnp.pad(q, zq)
            k = jnp.pad(k, zq)
            v = jnp.pad(v, zq)

        spec = P(None, axis, None, None)
        from demodel_tpu.parallel.collectives import shard_map_nocheck

        fn = shard_map_nocheck(
            functools.partial(ring_attention, axis_name=axis, causal=causal,
                              kv_len=kv_len),
            mesh, (spec, spec, spec), spec,
        )
        out = fn(q, k, v)
        return out[:, :T] if pad else out

    from demodel_tpu.utils import trace

    if isinstance(q, jax.core.Tracer) or not trace.enabled():
        return run()
    with trace.span("compute.ring-attention", batch=B, tokens=T, heads=H,
                    head_dim=D, ring=n, causal=causal):
        out = run()
        # demodel: allow(no-host-sync-in-hot-path) — observability-only
        # sync: the span must time the COMPUTE, not the async dispatch;
        # this branch runs only when the operator opted into export
        # tracing, never on the default (observe-tier) hot path
        jax.block_until_ready(out)
        return out
