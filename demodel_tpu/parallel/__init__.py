from demodel_tpu.parallel.mesh import make_mesh
from demodel_tpu.parallel.peer import PeerSet, ensure_artifacts

__all__ = ["make_mesh", "PeerSet", "ensure_artifacts"]
