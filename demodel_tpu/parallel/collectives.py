"""ICI-leg collectives for the delivery layer.

The reference has no device communication at all (SURVEY.md §2.3); its
"distributed" capability is HTTP blob exchange. In the rebuild, the DCN leg
is the peer cache (:mod:`demodel_tpu.parallel.peer`) and this module is the
ICI leg: once each host has landed its addressable shards, layout changes
(replicate a tensor, switch tp axis, gather for export) are expressed as
XLA resharding/collectives over the mesh — ``psum``/``all_gather``/
``ppermute`` inserted by the compiler or written explicitly via shard_map,
riding ICI rather than host networking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: experimental home
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map_nocheck(f, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` with the static replication checker disabled, across
    the jax API rename (``check_rep`` until 0.5, ``check_vma`` from 0.6).
    Collective outputs here ARE identical across the mapped axis, but the
    checker can't statically infer that in either spelling."""
    try:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def redistribute(arr: jax.Array, sharding: NamedSharding) -> jax.Array:
    """Change an array's layout on-device.

    A jitted identity with an output-sharding constraint: XLA emits the
    minimal collective (all-gather to replicate, all-to-all for an axis
    switch, slice for a split) over ICI — the idiomatic JAX way to move
    shards, rather than staging through host memory.
    """
    return jax.jit(lambda x: x, out_shardings=sharding)(arr)


def replicate(arr: jax.Array, mesh: Mesh) -> jax.Array:
    """All-gather a sharded array so every device holds the full tensor."""
    return redistribute(arr, NamedSharding(mesh, P()))


def allgather_axis(arr: jax.Array, mesh: Mesh, axis: str = "tp") -> jax.Array:
    """Explicit all-gather over one mesh axis via shard_map — the
    hand-written equivalent of :func:`replicate` for a single axis, used
    where the surrounding program is already shard_mapped."""
    ndim = arr.ndim

    def gather(x):
        return jax.lax.all_gather(x, axis, axis=0, tiled=True)

    in_spec = P(axis, *([None] * (ndim - 1)))
    out_spec = P(*([None] * ndim))
    return shard_map_nocheck(gather, mesh, (in_spec,), out_spec)(arr)


def psum_across(arr: jax.Array, mesh: Mesh, axis: str = "dp") -> jax.Array:
    """Sum per-shard blocks across a mesh axis (delivery checksum/
    verification aggregation across hosts).

    ``arr`` is treated as sharded along dim 0 over ``axis`` (shape[0] must
    divide by the axis size); the result is the elementwise sum of the
    per-device blocks, replicated everywhere — shape ``(shape[0]/n, ...)``.
    """
    n = mesh.shape[axis]
    if arr.ndim == 0 or arr.shape[0] % n:
        raise ValueError(
            f"psum_across: leading dim {arr.shape and arr.shape[0]} "
            f"not divisible by mesh axis {axis!r} size {n}"
        )

    def s(x):
        return jax.lax.psum(x, axis)

    in_spec = P(axis, *([None] * (arr.ndim - 1)))
    out_spec = P(*([None] * arr.ndim))
    return shard_map_nocheck(s, mesh, (in_spec,), out_spec)(arr)


@functools.partial(jax.jit, static_argnames=("chunk_elems",))
def _fingerprint(x: jax.Array, chunk_elems: int = 1 << 20) -> jax.Array:
    """Cheap on-device content fingerprint (float sums are layout-invariant
    up to reordering; used to cross-check shard placement across hosts
    without pulling tensors back to host)."""
    f = x.astype(jnp.float32).reshape(-1)
    return jnp.stack([f.sum(), jnp.abs(f).sum(), (f * f).sum()])


def fingerprint(arr: jax.Array) -> jax.Array:
    return _fingerprint(arr)
