"""Device mesh construction — one helper, every parallelism axis.

Axes (jax.sharding.Mesh names): ``dp`` data, ``sp`` sequence/context
(ring attention), ``ep`` expert, ``pp`` pipeline, ``tp`` tensor. ``dp`` and
``tp`` always exist (size 1 when unused) so ``NamedSharding`` specs written
against them stay valid on any mesh; the optional axes appear only when
requested. The leftover device factor lands in ``tp`` unless ``tp`` was
pinned, in which case it lands in ``dp`` — e.g. ``make_mesh(8)`` →
``{'dp': 1, 'tp': 8}``; ``make_mesh(8, tp=1, pp=4)`` → ``{'dp': 2,
'pp': 4, 'tp': 1}``.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(n_devices: int | None = None, *, dp: int | None = None,
              sp: int | None = None, ep: int | None = None,
              pp: int | None = None, tp: int | None = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    n = len(devices)

    fixed = 1
    for v in (dp, sp, ep, pp, tp):
        if v is not None:
            if v <= 0:
                raise ValueError("mesh axis sizes must be positive")
            fixed *= v
    if n % fixed != 0:
        raise ValueError(f"{n} devices not divisible by requested axes "
                         f"(product {fixed})")
    rest = n // fixed
    if tp is None:
        tp = rest
        rest = 1
    if dp is None:
        dp = rest
        rest = 1
    if rest != 1:
        raise ValueError(f"axis sizes {fixed * rest} != device count {n}")

    names, sizes = ["dp"], [dp]
    for name, size in (("sp", sp), ("ep", ep), ("pp", pp)):
        if size is not None:
            names.append(name)
            sizes.append(size)
    names.append("tp")
    sizes.append(tp)
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))
