"""Peer shard cache over DCN.

The reference's peer story is "run the proxy near your friends and re-serve
blobs over HTTP" (``README.md:5-10``). The rebuild makes it first-class:
every proxy exposes native ``/peer/{index,meta,object}`` endpoints over its
content-addressed store (served by the C++ data plane, range-aware), and
this module is the client side — discover which peer holds which key, fetch
missing artifacts DCN-first with digest verification and resume, and only
fall back to the upstream registry when no peer has the bytes.

On-device redistribution after landing (the ICI leg) lives in
:mod:`demodel_tpu.parallel.collectives`.
"""

from __future__ import annotations


import re
import threading
import time
from dataclasses import dataclass, field
from typing import ClassVar

import requests

from demodel_tpu.parallel.placement import HashRing
from demodel_tpu.store import Store
from demodel_tpu.utils import trace
from demodel_tpu.utils.env import env_int
from demodel_tpu.utils.faults import (
    DigestMismatch,
    PeerHealth,
    RetryPolicy,
    request_with_retry,
)
from demodel_tpu.utils.logging import get_logger

log = get_logger("peer")


class PeerGossip:
    """Process-wide, versioned possession index over the peer set.

    Two feeds, one consumer contract:

    - **piggyback**: every ``/peer/index`` download anywhere in the
      process (:meth:`PeerSet.index`) is observed here for free — locate
      calls and striping rotations read the freshest answer any
      component already paid for;
    - **background refresh**: peers enrolled via :meth:`track` are
      re-polled every ``DEMODEL_SWARM_INDEX_REFRESH_S`` seconds off the
      critical path, replacing the old per-pull probe round — pull #2
      onward builds its rotation with zero liveness traffic.

    Entries are versioned (monotonic per peer) and bounded
    (``DEMODEL_SWARM_INDEX_KEYS`` keys per peer, newest fetch wins);
    deliberately NOT fed into the breakers — gossip is advisory
    liveness, and a background poller must never burn a breaker's
    half-open probe slot or open breakers behind a live pull's back.
    """

    _shared: ClassVar["PeerGossip | None"] = None
    _shared_lock: ClassVar[threading.Lock] = threading.Lock()

    def __init__(self, refresh_s: float | None = None,
                 max_keys: int | None = None):
        self.refresh_s = refresh_s if refresh_s is not None else float(
            env_int("DEMODEL_SWARM_INDEX_REFRESH_S", 2, minimum=1))
        self.max_keys = max_keys if max_keys is not None else env_int(
            "DEMODEL_SWARM_INDEX_KEYS", 65536, minimum=16)
        self._lock = threading.Lock()
        #: peer → (version, keys-or-None, monotonic ts, ok)
        self._entries: dict[str, tuple[int, frozenset | None, float, bool]] = {}
        self._tracked: set[str] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @classmethod
    def shared(cls) -> "PeerGossip":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls()
            return cls._shared

    @classmethod
    def reset_shared(cls) -> None:
        """Drop the process-wide registry, stopping its refresher
        (tests only)."""
        with cls._shared_lock:
            inst, cls._shared = cls._shared, None
        if inst is not None:
            inst.stop()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)

    # -- feeds -----------------------------------------------------------
    def observe(self, peer: str, keys: set[str] | None,
                ok: bool = True) -> None:
        """Merge one index outcome (a real download or a failed one).
        ``keys=None`` with ``ok=False`` records liveness without data."""
        peer = peer.rstrip("/")
        frozen = None
        if keys is not None:
            if len(keys) > self.max_keys:
                # bounded: keep a deterministic subset — membership tests
                # may false-miss, and the locate fallback covers that
                frozen = frozenset(sorted(keys)[: self.max_keys])
            else:
                frozen = frozenset(keys)
        with self._lock:
            version = self._entries.get(peer, (0,))[0] + 1
            self._entries[peer] = (version, frozen, time.monotonic(), ok)

    def track(self, peers: list) -> None:
        """Enroll peers for background refresh (idempotent; starts the
        refresher thread on first use)."""
        cleaned = {p.rstrip("/") for p in peers if p}
        if not cleaned:
            return
        with self._lock:
            self._tracked |= cleaned
            start = self._thread is None and not self._stop.is_set()
            if start:
                self._thread = threading.Thread(
                    target=self._refresh_loop, name="peer-gossip",
                    daemon=True)
        if start:
            self._thread.start()

    # -- reads -----------------------------------------------------------
    def _fresh(self, peer: str,
               max_age: float) -> tuple[frozenset | None, bool] | None:
        with self._lock:
            e = self._entries.get(peer.rstrip("/"))
        if e is None or time.monotonic() - e[2] > max_age:
            return None
        return e[1], e[3]

    def keys(self, peer: str, max_age: float | None = None) -> frozenset | None:
        """Fresh possession set for ``peer``, or None when gossip has
        nothing current (caller falls back to a real index fetch)."""
        age = max_age if max_age is not None else 3 * self.refresh_s
        e = self._fresh(peer, age)
        if e is None:
            return None
        ks, ok = e
        return ks if ok else None

    def split(self, peers: list, max_age: float | None = None
              ) -> tuple[list, list, list]:
        """``(alive, dead, unknown)`` partition of ``peers`` by gossip
        freshness — the replacement for the per-pull probe round: only
        ``unknown`` (never-heard-from) peers still need a real probe."""
        age = max_age if max_age is not None else 3 * self.refresh_s
        alive: list = []
        dead: list = []
        unknown: list = []
        for p in peers:
            e = self._fresh(p, age)
            if e is None:
                unknown.append(p)
            elif e[1]:
                alive.append(p)
            else:
                dead.append(p)
        return alive, dead, unknown

    def describe(self) -> dict[str, dict]:
        """Statusz view: per-peer freshness, never the key sets."""
        now = time.monotonic()
        with self._lock:
            return {
                peer: {"version": v, "keys": len(k) if k is not None else 0,
                       "age_sec": round(now - ts, 3), "ok": ok}
                for peer, (v, k, ts, ok) in sorted(self._entries.items())
            }

    # -- refresher -------------------------------------------------------
    def _refresh_loop(self) -> None:
        session = requests.Session()
        while not self._stop.wait(self.refresh_s):
            with self._lock:
                peers = sorted(self._tracked)
            for peer in peers:
                if self._stop.is_set():
                    return
                self._refresh_one(session, peer)

    def _refresh_one(self, session: requests.Session, peer: str) -> None:
        # span-free, single attempt: a background refresh failing against
        # a dead peer is routine liveness data (observe ok=False), not an
        # incident — it must not trip the flight recorder's error-root
        # dump, and the next refresh tick is the retry
        try:
            r = session.get(f"{peer}/peer/index", timeout=5.0)
            r.raise_for_status()
            body = r.json()
            entries = body.get("keys", ()) if isinstance(body, dict) else ()
            keys = {str(e["key"]) for e in entries
                    if isinstance(e, dict) and "key" in e}
            self.observe(peer, keys, ok=True)
        except (requests.RequestException, OSError, ValueError,
                TypeError):
            self.observe(peer, None, ok=False)


def _peer_streams() -> int:
    """Connections per large-object peer transfer (``DEMODEL_PEER_STREAMS``).

    One TCP stream rarely fills a DCN link (VERDICT r1 weak #1); slicing an
    object across N range requests multiplies the in-flight window. The
    native side clamps to sensible slice sizes, so a large default is safe
    — but only when cores exist to run the streams: on a host with few
    CPUs the extra sockets just contend (measured −18% at 1 core, 8
    streams vs 1), so the unset-env default is clamped to the core
    count. An explicit env value always wins. Resolution lives in
    utils.env so the dep-light statusz surface reports the same value."""
    from demodel_tpu.utils.env import default_peer_streams

    return default_peer_streams()


@dataclass
class PeerStats:
    from_peers: int = 0
    from_upstream: int = 0
    peer_bytes: int = 0
    misses: list = field(default_factory=list)


class PeerSet:
    """A set of peer proxy base URLs (e.g. ``http://host-a:8080``)."""

    def __init__(self, peers: list[str], timeout: float = 30.0,
                 index_ttl: float = 5.0,
                 health: PeerHealth | None = None,
                 policy: RetryPolicy | None = None):
        self.peers = [p.rstrip("/") for p in peers]
        self.timeout = timeout
        #: shared wire-robustness state: breakers are process-wide, so a
        #: peer the sharded pull found dead is skipped here too (and vice
        #: versa) — the whole point of PeerHealth being a registry
        self._health = health if health is not None else PeerHealth.shared()
        self._policy = policy if policy is not None else RetryPolicy()
        #: floor between forced index refreshes — a pull with many misses
        #: must not re-download every peer's full index once per artifact
        self.index_ttl = index_ttl
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._ring_cache: HashRing | None = None
        self._index_cache: dict[str, tuple[set[str], float]] = {}
        #: serializes the index *download* per peer so a cold-cache fan-out
        #: of fetch workers doesn't stampede /peer/index N times at once
        self._index_fetch_locks: dict[str, threading.Lock] = {}

    @property
    def session(self) -> requests.Session:
        """Per-thread session: parallel shard fetches share one PeerSet."""
        s = getattr(self._tls, "session", None)
        if s is None:
            s = self._tls.session = requests.Session()
        return s

    def index(self, peer: str, refresh: bool = False) -> dict[str, str]:
        """``{key: sha256-or-""}`` present on ``peer`` (cached per instance;
        ``refresh`` is rate-limited to once per ``index_ttl`` seconds)."""
        def fresh_enough(cached) -> bool:
            return cached is not None and (
                not refresh or time.monotonic() - cached[1] < self.index_ttl
            )

        with self._lock:
            cached = self._index_cache.get(peer)
            fetch_lock = self._index_fetch_locks.setdefault(peer, threading.Lock())
        if fresh_enough(cached):
            return cached[0]
        with fetch_lock:
            # double-check: another worker may have fetched while we waited
            with self._lock:
                cached = self._index_cache.get(peer)
            if fresh_enough(cached):
                return cached[0]
            try:
                # per-peer single-flight lock guarding exactly this
                # download (a cold-cache fetch fan-out must not stampede
                # /peer/index); the instance-wide self._lock is never
                # held across it — lock-io recognizes the pattern now,
                # so no allow() pragma is needed
                r = request_with_retry(
                    self.session, "GET", f"{peer}/peer/index",
                    policy=self._policy, health=self._health, peer=peer,
                    timeout=self.timeout, what=f"peer index {peer}")
                body = r.json()
                # shape-validate: a peer answering 200 with junk (captive
                # portal, wrong service on the port) must degrade to an
                # empty index, not crash the pull (peer-json-shape)
                entries = body.get("keys", ()) if isinstance(body, dict) else ()
                keys = {str(e["key"]): str(e.get("sha256") or "")
                        for e in entries
                        if isinstance(e, dict) and "key" in e}
                PeerGossip.shared().observe(peer, set(keys))
            except (requests.RequestException, ValueError, TypeError) as e:
                log.warning("peer %s index failed: %s", peer, e)
                keys = {}
                PeerGossip.shared().observe(peer, None, ok=False)
            with self._lock:
                self._index_cache[peer] = (keys, time.monotonic())
            return keys

    def _ring(self) -> HashRing:
        """Consistent-hash ring over this peer set (built once): the
        same ring the striping rotation places files with, so the owner
        computed here is the peer most likely to hold the key."""
        ring = self._ring_cache
        if ring is None:
            ring = self._ring_cache = HashRing(self.peers)
        return ring

    def locate(self, key: str) -> str | None:
        """Peer advertising ``key``, ring-first: the consistent-hash
        owner (and its successor) answer from gossip or the cached index
        without any broadcast — matching how the striping rotation
        placed the key — and only a ring miss falls back to the full
        probe scan. Open-breaker peers are skipped until their half-open
        probe succeeds — a dead friend must not cost every lookup a
        connect timeout; the upstream fallback covers the gap."""
        with trace.span("peer-locate", key=key) as sp:
            gossip = PeerGossip.shared()
            ring_owners = self._ring().owners(key, 2)
            for peer in ring_owners:
                if not self._health.admissible(peer):
                    continue
                known = gossip.keys(peer)
                if known is not None:
                    # fresh gossip answers without a dial either way; a
                    # stale "no" is caught by the refresh scan below
                    if key in known:
                        sp.set_attr("peer", peer)
                        sp.set_attr("via", "ring-gossip")
                        return peer
                    continue
                if key in self.index(peer):
                    sp.set_attr("peer", peer)
                    sp.set_attr("via", "ring-index")
                    return peer
            for refresh in (False, True):
                for peer in self.peers:
                    if not self._health.admissible(peer):
                        continue  # read-only: index() may serve cached
                    if key in self.index(peer, refresh=refresh):
                        sp.set_attr("peer", peer)
                        return peer
            return None

    def locate_digest(self, digest: str) -> tuple[str, str] | None:
        """``(peer, their_key)`` for any object whose sha256 matches —
        content-address dedup across differing cache keys (the MITM'd CDN
        URL vs the canonical resolve URL of the same blob)."""
        for refresh in (False, True):
            for peer in self.peers:
                if not self._health.admissible(peer):
                    continue
                for k, sha in self.index(peer, refresh=refresh).items():
                    if sha == digest:
                        return peer, k
        return None

    def fetch_into(self, store: Store, key: str,
                   expected_digest: str | None = None) -> bool:
        """Copy ``key`` from whichever peer has it into the local store.

        Resumes partials, verifies the digest recorded in the peer's meta
        (or ``expected_digest``), and stores the peer's meta sidecar
        unchanged so the object is indistinguishable from a locally-cached
        one. Returns False when no peer has the key.

        Concurrent calls for one key collapse to a single transfer through
        the store's shared single-flight registry
        (:mod:`demodel_tpu.tier`): one caller leads, the rest wait on the
        outcome and re-read the store. The ``peer:`` key prefix keeps
        these admission flights apart from the tier read path's
        watermark flights on the same registry.
        """
        if store.has(key):
            return True
        from demodel_tpu import tier
        flights = tier.shared(store).flights
        got = flights.do(
            "peer:" + key,
            lambda: store.has(key)  # a previous leader already landed it
            or self._fetch_into_once(store, key, expected_digest))
        if got is None:  # waiter: the leader's outcome is in the store
            return store.has(key)
        return bool(got)

    def _fetch_into_once(self, store: Store, key: str,
                         expected_digest: str | None = None) -> bool:
        """One un-collapsed :meth:`fetch_into` attempt (the single-flight
        leader's body). Transport failures degrade to False — the caller
        falls over to upstream — so the flight always finishes ok and
        waiters re-read the store rather than re-dialing peers."""
        remote_key = key
        peer = self.locate(key)
        if peer is None and expected_digest:
            # no peer has this exact key, but one may hold the same CONTENT
            # under a different key — fetch by content address
            # demodel: allow(atomic-snapshot) — sequential best-effort
            # lookups, not one snapshot: a locate miss followed by a
            # digest hit needs no cross-hold consistency (the fetch
            # itself re-verifies the digest end-to-end)
            hit = self.locate_digest(expected_digest)
            if hit is not None:
                peer, remote_key = hit
                log.info("peer %s holds digest %s as %s; deduping", peer,
                         expected_digest[:12], remote_key)
        if peer is None:
            return False
        try:
            meta = request_with_retry(
                self.session, "GET", f"{peer}/peer/meta/{remote_key}",
                policy=self._policy, health=self._health, peer=peer,
                timeout=self.timeout, what=f"peer meta {remote_key}")
            peer_meta = meta.json()
            if not isinstance(peer_meta, dict):
                raise IOError(f"peer meta for {remote_key} is not an object")
            want = expected_digest or peer_meta.get("sha256")

            if self._native_fetch(store, peer, key, want, peer_meta,
                                  remote_key=remote_key):
                return True

            self._stream_object_into(store, peer, key, remote_key, want,
                                     peer_meta)
            return True
        except (requests.RequestException, OSError,
                ValueError, TypeError) as e:
            # ValueError/TypeError: malformed peer meta JSON (old requests
            # raises json.JSONDecodeError=ValueError; a non-dict body makes
            # .get raise TypeError) must fail over to upstream, not crash
            # the whole pull (peer-json-shape)
            log.warning("peer fetch of %s from %s failed: %s", key, peer, e)
            return False

    def _stream_object_into(self, store: Store, peer: str, key: str,
                            remote_key: str, want: str | None,
                            peer_meta: dict) -> None:
        """Stream one object into the store under the retry policy: a
        transfer that dies mid-body keeps its partial and the next attempt
        resumes it with a Range request — chunk-level recovery, not a
        restart. Digest mismatches drop the partial and never retry
        (re-reading poisoned bytes cannot converge); the caller's degrade
        contract falls over to upstream instead."""

        def one_attempt() -> None:
            partial = store.partial_size(key)
            headers: dict = {}
            if partial > 0:
                headers["Range"] = f"bytes={partial}-"
            # raw streaming GET (resume semantics live here, not in
            # request_with_retry) — carry the ambient span's traceparent
            headers = trace.inject_headers(headers) or headers
            r = self.session.get(f"{peer}/peer/object/{remote_key}",
                                 headers=headers, stream=True,
                                 timeout=max(self.timeout, 300))
            try:
                resumed = partial > 0 and r.status_code == 206
                r.raise_for_status()
                w = store.begin(key, resume=resumed)
                try:
                    for chunk in r.iter_content(1 << 20):
                        if chunk:
                            w.append(chunk)
                    digest = w.digest()
                    if want and digest != want:
                        w.abort(keep_partial=False)
                        raise DigestMismatch(
                            f"peer digest mismatch for {key}: "
                            f"{digest} != {want}")
                    w.commit(peer_meta)
                except BaseException:
                    if w._open:  # noqa: SLF001
                        w.abort(keep_partial=True)
                    raise
            finally:
                # a failed attempt must not strand a half-consumed
                # keep-alive connection: the serving peer's bounded pool
                # holds a worker per connection, and the retry's own
                # resume would queue behind the one it abandoned
                r.close()

        with trace.span("peer-stream", key=remote_key, peer=peer):
            self._policy.call(
                one_attempt, peer=peer, health=self._health,
                what=f"peer object {remote_key} from {peer} "
                     "(each retry resumes the kept partial)")

    def fetch_to_memory(self, key: str, expected_digest: str | None = None,
                        eager_verify: bool = True, budget=None):
        """Fetch ``key`` (located by key or content digest) from a peer
        straight into a host landing buffer — the zero-disk leg of
        cold-pull→HBM. Returns ``(numpy uint8 buffer, peer_meta)`` or
        ``None`` when no peer has the bytes / the native path can't run.

        The caller owns persisting the buffer into a store (asynchronously,
        off the delivery critical path). ``eager_verify=False`` skips the
        inline sha256 pass (optimistic delivery): the caller's background
        cache commit re-hashes the same bytes and MUST surface a mismatch
        (see ``Fetcher.flush_writes`` / ``Placement.finalize``) — on a
        starved host the inline hash otherwise serializes with the
        transfer it is guarding."""
        import ctypes

        import numpy as np

        from demodel_tpu import native

        remote_key = key
        peer = self.locate(key)
        if peer is None and expected_digest:
            # demodel: allow(atomic-snapshot) — same sequential fallback
            # as fetch_into above: no cross-hold consistency expected,
            # the transfer re-verifies the digest
            hit = self.locate_digest(expected_digest)
            if hit is not None:
                peer, remote_key = hit
        if peer is None:
            return None
        m = re.match(r"^http://(\[[0-9a-fA-F:]+\]|[^:/]+)(?::(\d+))?/?$", peer)
        if m is None:
            return None  # https/odd peers use the store path
        try:
            r = request_with_retry(
                self.session, "GET", f"{peer}/peer/meta/{remote_key}",
                policy=self._policy, health=self._health, peer=peer,
                timeout=self.timeout, what=f"peer meta {remote_key}")
            peer_meta = r.json()
            # same shape-validation contract as fetch_into: junk meta from
            # a peer degrades to "no peer copy", never a crashed delivery
            size = int(peer_meta.get("size") or 0) \
                if isinstance(peer_meta, dict) else 0
        except (requests.RequestException, ValueError, TypeError) as e:
            log.warning("peer %s meta for %s failed: %s", peer, remote_key, e)
            return None
        if size <= 0:
            return None
        want = expected_digest or peer_meta.get("sha256") or ""
        host, port = m.group(1).strip("[]"), int(m.group(2) or 80)
        if budget is not None:
            # host RAM is committed HERE — the budget gates allocation, not
            # just queue admission, so concurrent fetches of huge shards
            # wait before touching memory
            with trace.span("budget-wait", bytes=size, key=remote_key):
                budget.acquire(size)
        try:
            buf = np.empty(size, dtype=np.uint8)
            errbuf = ctypes.create_string_buffer(512)
            with trace.span("peer-fetch-memory", key=remote_key,
                            peer=peer, bytes=size):
                n = native.lib().dm_peer_fetch_into(
                    host.encode(), port,
                    f"/peer/object/{remote_key}".encode(),
                    size, _peer_streams(),
                    (want if eager_verify else "").encode(),
                    buf.ctypes.data_as(ctypes.c_void_p), errbuf, 512,
                )
            if n != size:
                log.warning("peer memory fetch of %s from %s failed: %s", key,
                            peer, errbuf.value.decode(errors="replace"))
                if budget is not None:
                    budget.release(size)
                return None
        except BaseException:
            if budget is not None:
                budget.release(size)
            raise
        return buf, peer_meta

    def _native_fetch(self, store: Store, peer: str, key: str,
                      want: str | None, peer_meta: dict,
                      remote_key: str | None = None) -> bool:
        """Bulk transfer via the C++ data plane: socket(s) → store with
        digest verify, no Python per-chunk work. Large objects with a known
        size fan out over N range connections (``dm_peer_fetch_parallel``,
        RangeWriter); small/unknown sizes take the single-socket resume path
        (``dm_peer_fetch``). Returns False to fall back to the requests path
        (https peers, native errors)."""
        m = re.match(r"^http://(\[[0-9a-fA-F:]+\]|[^:/]+)(?::(\d+))?/?$", peer)
        if m is None:
            # https peers / odd URL shapes ride the requests path; log at
            # debug so a silently slow pull is diagnosable (ADVICE r1 #5)
            log.debug("peer %s not native-fetchable (need http://host[:port]); "
                      "using requests path", peer)
            return False
        import ctypes
        import json as _json

        from demodel_tpu import native

        host, port = m.group(1).strip("[]"), int(m.group(2) or 80)
        errbuf = ctypes.create_string_buffer(512)
        size = int(peer_meta.get("size") or 0)
        streams = _peer_streams()
        n = native.lib().dm_peer_fetch_parallel(
            store._h, host.encode(), port,  # noqa: SLF001 — data-plane handoff
            f"/peer/object/{remote_key or key}".encode(), key.encode(), size,
            streams, (want or "").encode(), _json.dumps(peer_meta).encode(),
            errbuf, 512,
        )
        if n < 0:
            log.warning("native peer fetch of %s from %s failed: %s "
                        "(falling back to requests)", key, peer,
                        errbuf.value.decode(errors="replace"))
            return False
        return True


def ensure_artifacts(
    store: Store,
    artifacts: list,
    peers: PeerSet | None,
    upstream_fetch=None,
) -> PeerStats:
    """Make every artifact local: peer-first over DCN, upstream fallback.

    ``artifacts`` is a list of objects/dicts with ``key``/``sha256``/``name``;
    ``upstream_fetch(artifact)`` is invoked for anything no peer holds.
    """
    from demodel_tpu.registry.base import parallel_fetch

    stats = PeerStats()
    stats_lock = threading.Lock()
    t0 = time.perf_counter()

    def ensure_one(art):
        key = art.key if hasattr(art, "key") else art["key"]
        sha = art.sha256 if hasattr(art, "sha256") else art.get("sha256")
        name = art.name if hasattr(art, "name") else art.get("name", key)
        if store.has(key):
            return
        if peers is not None and peers.fetch_into(store, key, expected_digest=sha):
            with stats_lock:
                stats.from_peers += 1
                stats.peer_bytes += store.size(key)
            return
        if upstream_fetch is not None:
            upstream_fetch(art)
            with stats_lock:
                stats.from_upstream += 1
        else:
            with stats_lock:
                stats.misses.append(name)

    # dedup by key: concurrent writers on one key would collide in the store
    unique: dict[str, object] = {}
    for art in artifacts:
        k = art.key if hasattr(art, "key") else art["key"]
        unique.setdefault(k, art)
    parallel_fetch(list(unique.values()), ensure_one)
    if stats.from_peers or stats.from_upstream:
        log.info(
            "ensured %d artifacts in %.2fs: %d from peers (%.1f MB over DCN), %d upstream",
            len(artifacts), time.perf_counter() - t0, stats.from_peers,
            stats.peer_bytes / 1e6, stats.from_upstream,
        )
    return stats
