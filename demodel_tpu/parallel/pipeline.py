"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

Stages are a STACKED pytree (leading axis = stage) sharded over ``pp``;
microbatches stream through a ``lax.scan`` over the classic GPipe schedule
(n_micro + n_stages - 1 ticks), with per-tick stage io rotated by
``ppermute``-equivalent shifts XLA derives from the shardings. Everything
is shape-static and differentiable — ``jax.grad`` through the schedule
matches sequential execution exactly (tested).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stages(stages: list) -> dict:
    """List of per-stage pytrees (identical structure) → one stacked pytree
    with a leading stage axis — the shardable representation."""
    if not stages:
        raise ValueError("no stages")
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *stages)


def unstack_stages(stacked, n: int) -> list:
    return [jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(n)]


def microbatch(x: jnp.ndarray, n_micro: int) -> jnp.ndarray:
    """[B, ...] → [n_micro, B/n_micro, ...] (validated split)."""
    if x.shape[0] % n_micro != 0:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


def pipeline_apply(stage_fn, stages, xmb, mesh: Mesh | None = None,
                   x_spec: P | None = None):
    """Run microbatched input [M, b, ...] through all stages in GPipe order.

    ``stage_fn(stage_params, activation) -> activation``; ``stages`` is the
    stacked pytree. Returns [M, b, ...] outputs. The schedule uses a
    rotating buffer over M + S - 1 ticks: at tick t, stage s processes
    microbatch t - s (when in range) — the standard bubble, no recompute.
    """
    n_stages = jax.tree.leaves(stages)[0].shape[0]
    M = xmb.shape[0]

    def one_micro(x):
        # sequential composition of all stages for one microbatch; under
        # pjit with `stages` sharded over pp, each lax.scan step's compute
        # lands on the stage-owner while activations flow via collectives
        def body(carry, stage):
            out = stage_fn(stage, carry)
            if mesh is not None and x_spec is not None:
                out = lax.with_sharding_constraint(
                    out, NamedSharding(mesh, x_spec))
            return out, None

        out, _ = lax.scan(body, x, stages)
        return out

    def run():
        # microbatches are independent given the stage weights: vmap
        # expresses the pipeline's width; XLA overlaps stage compute across
        # microbatches in the scheduled program (the GPipe bubble shows up
        # as the dependency depth, not as Python control flow)
        return jax.vmap(one_micro)(xmb)

    # eager calls run under a compute span when EXPORT tracing is opted
    # in (jit-traced calls always skip it — a span inside jit would
    # record trace-time once): the GPipe schedule shows up in the
    # critical-path report + stage histograms with the other planes. The
    # span syncs the result, so it stays off the default observe tier —
    # default-config callers keep fully async dispatch
    from demodel_tpu.utils import trace

    if isinstance(xmb, jax.core.Tracer) or not trace.enabled():
        return run()
    with trace.span("compute.gpipe", stages=int(n_stages), microbatches=int(M)):
        out = run()
        # demodel: allow(no-host-sync-in-hot-path) — observability-only
        # sync so the span times the schedule's compute, not its
        # dispatch; only runs under opted-in export tracing
        jax.block_until_ready(out)
        return out


def pipeline_stage_spec(ndim: int) -> P:
    """PartitionSpec for a stacked stage pytree leaf of ``ndim`` dims
    (stage axis over pp, rest replicated)."""
    return P("pp", *([None] * (ndim - 1)))


def shard_stages(stages, mesh: Mesh):
    """Place a stacked stage pytree with the stage axis over ``pp``."""
    def put(x):
        return jax.device_put(
            x, NamedSharding(mesh, pipeline_stage_spec(x.ndim)))

    return jax.tree.map(put, stages)
