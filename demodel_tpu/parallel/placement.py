"""Consistent-hash placement + swarm chunk possession (dep-light).

Two primitives the pod-scale swarm pull is built from:

- :class:`HashRing` — classic consistent hashing with virtual nodes
  (à la distributed caches): a stable key→node map over a peer set, so
  every host computes the same owner for a key/chunk WITHOUT any
  broadcast, and a node's death moves only its own arc to the ring
  successors instead of reshuffling everything.
- :class:`ChunkBoard` — one pull's chunk possession state on one host:
  which fixed-grid chunks of which manifest files have landed, plus the
  bytes themselves, so the restore server can re-serve them to swarm
  siblings (``/swarm/{pull}/{host}/chunk/...``). Summaries are bounded
  (a bitmap per file) and versioned, so gossip merges are
  last-writer-wins per board, never a diff protocol.

This module is deliberately stdlib-only: the restore server and statusz
read boards through a ``sys.modules`` peek, and a dep-light serve node
must be able to host the swarm surface without importing jax/numpy.
"""

from __future__ import annotations

import hashlib
import threading
from bisect import bisect_right

from demodel_tpu.utils.env import env_int


def _point(token: str) -> int:
    """64-bit ring coordinate of a token (stable across hosts/runs)."""
    return int.from_bytes(
        hashlib.sha256(token.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring with virtual nodes over an ordered node set.

    ``vnodes`` points per node (``DEMODEL_SWARM_VNODES``, default 256)
    smooth the arc sizes to within a few percent; every host building a
    ring over the same node ids gets the identical key→node map — the
    property that lets N pullers partition a chunk grid with zero
    coordination traffic.
    """

    def __init__(self, nodes: list[str], vnodes: int | None = None):
        if vnodes is None:
            # 256 points/node holds the worst arc within a few percent of
            # ideal (measured max_share 0.256 vs 0.25 at N=4) — the swarm
            # wall-clock is bounded by the LARGEST owned share, so lumpy
            # arcs directly cost O(size/N) time
            vnodes = env_int("DEMODEL_SWARM_VNODES", 256, minimum=1)
        self.nodes = sorted(set(nodes))
        self._points: list[tuple[int, str]] = sorted(
            (_point(f"{n}#{i}"), n)
            for n in self.nodes for i in range(vnodes))
        self._keys = [p for p, _ in self._points]

    def owner(self, key: str) -> str | None:
        """The node owning ``key`` (None on an empty ring)."""
        owners = self.owners(key, 1)
        return owners[0] if owners else None

    def owners(self, key: str, n: int) -> list[str]:
        """Up to ``n`` DISTINCT nodes in ring order from ``key``'s point —
        the ownership succession: ``owners(k, 2)[1]`` is who re-owns the
        chunk when the primary dies."""
        if not self._points or n <= 0:
            return []
        out: list[str] = []
        i = bisect_right(self._keys, _point(key))
        for step in range(len(self._points)):
            node = self._points[(i + step) % len(self._points)][1]
            if node not in out:
                out.append(node)
                if len(out) >= min(n, len(self.nodes)):
                    break
        return out


def spread_key(token: str) -> int:
    """Stable pseudo-random sort key — the rarest-first tie-break that
    decorrelates swarm hosts' origin request orders without RNG state."""
    return _point(token)


def bounded_assign(ring: "HashRing", items: list[str]) -> dict[str, str]:
    """Consistent-hash placement with BOUNDED LOADS (Mirrokni et al.):
    each item goes to the first node on its ring succession with
    capacity left, capacity = ceil(len(items)/len(nodes)).

    Pure ring ownership over a small item set (a manifest's chunk grid)
    is lumpy — a 4-host swarm measured a 33% worst arc, and the swarm's
    wall-clock is bounded by the LARGEST owned share — so primaries are
    capacity-capped while succession (death re-ownership) still walks
    the raw ring order. Deterministic: every host computes the identical
    assignment from the same inputs, no coordination."""
    if not ring.nodes:
        return {}
    cap = (len(items) + len(ring.nodes) - 1) // len(ring.nodes)
    load = {n: 0 for n in ring.nodes}
    out: dict[str, str] = {}
    # hash-ordered walk: overflow spill decorrelates from file order, so
    # no node's overflow lands on one file's contiguous tail
    for item in sorted(items, key=spread_key):
        for node in ring.owners(item, len(ring.nodes)):
            if load[node] < cap:
                load[node] += 1
                out[item] = node
                break
    return out


def chunk_count(size: int, chunk_bytes: int) -> int:
    return max(1, (int(size) + chunk_bytes - 1) // chunk_bytes)


def chunk_span(size: int, chunk_bytes: int, index: int) -> tuple[int, int]:
    """``(offset, length)`` of chunk ``index`` in an object of ``size``."""
    off = index * chunk_bytes
    return off, min(chunk_bytes, int(size) - off)


# Knob defaults delegate to utils.env (the shared stdlib-only home) so
# the dep-light statusz effective-config surface reports the same
# defaults the scheduler actually uses — a copied literal drifts, a
# shared resolver cannot (the FILL_TIMEOUT 15-vs-60 doc bug in PR 8 was
# exactly that drift). Importing THIS module still runs the parallel
# package's __init__ (jax), which is why statusz reads utils.env, not us.


def default_chunk_bytes() -> int:
    from demodel_tpu.utils.env import default_swarm_chunk_mb

    return default_swarm_chunk_mb() << 20


def default_fill_timeout() -> float:
    from demodel_tpu.utils.env import default_swarm_fill_timeout

    return default_swarm_fill_timeout()


def default_origin_streams() -> int:
    from demodel_tpu.utils.env import default_swarm_origin_streams

    return default_swarm_origin_streams()


def reap_enabled() -> bool:
    from demodel_tpu.utils.env import swarm_reap_enabled

    return swarm_reap_enabled()


def _bitmap_hex(have: set[int], n: int) -> str:
    bm = bytearray((n + 7) // 8)
    for i in have:
        bm[i >> 3] |= 1 << (i & 7)
    return bm.hex()


def bitmap_indices(hex_str: str, n: int) -> set[int]:
    """Inverse of the summary bitmap: advertised chunk indices < ``n``."""
    try:
        bm = bytes.fromhex(hex_str)
    except ValueError:
        return set()
    return {i for i in range(min(n, len(bm) * 8)) if bm[i >> 3] >> (i & 7) & 1}


def _charge_ram(delta: int) -> None:
    """Charge (or release, negative) chunk-board bytes against the
    shared host-RAM tier budget (``demodel_tpu.tier.ram_budget``):
    landing swarm chunks push mmap'd hot objects out of the tier
    instead of overshooting host RAM. Lazy import keeps this module
    importable without the store stack (the tier module is dep-light
    but not stdlib-only); called OUTSIDE the board lock so the budget
    lock never nests under it."""
    if not delta:
        return
    from demodel_tpu import tier
    budget = tier.ram_budget()
    if delta > 0:
        budget.charge(delta)
        if budget.over() > 0:
            tier.shed_ram()
    else:
        budget.release(-delta)


class ChunkBoard:
    """One host's chunk possession + bytes for one swarm pull.

    Thread-safe. ``put`` bumps a monotonic version so a polled summary is
    orderable: gossip keeps the highest-version summary per board and
    drops stale reorderings. Chunks are retained until :meth:`clear` —
    the board IS the peer-serve surface; a host that dropped a chunk the
    swarm still needs would silently push its siblings back to origin.
    Held bytes are charged to the shared host-RAM tier budget
    (:func:`demodel_tpu.tier.ram_budget`) and released on reap/clear,
    so a pull in flight evicts hot-tier objects before it can
    overshoot host RAM.
    """

    def __init__(self, pull_id: str, host_id: str):
        self.pull_id = pull_id
        self.host_id = host_id
        self._lock = threading.Lock()
        self._files: dict[str, int] = {}          # file key → chunk count
        self._chunks: dict[tuple[str, int], bytes] = {}
        #: chunks the reaper freed: landed once, bytes dropped because
        #: every live sibling already holds them — progress accounting
        #: keeps them (the chunk DID cross the wire), the serve surface
        #: and the summary bitmap do not (we can no longer serve them)
        self._reaped: set[tuple[str, int]] = set()
        self._bytes_reaped = 0
        self._version = 0

    def add_file(self, key: str, n_chunks: int) -> None:
        with self._lock:
            self._files[key] = int(n_chunks)
            self._version += 1

    def put(self, key: str, index: int, data: bytes) -> None:
        data = bytes(data)
        with self._lock:
            if key not in self._files:
                raise KeyError(f"unknown swarm file {key!r}")
            prev = self._chunks.get((key, index))
            self._chunks[(key, index)] = data
            self._reaped.discard((key, index))  # a re-fetch un-reaps
            self._version += 1
        _charge_ram(len(data) - (len(prev) if prev is not None else 0))

    def get(self, key: str, index: int) -> bytes | None:
        with self._lock:
            return self._chunks.get((key, index))

    def has(self, key: str, index: int) -> bool:
        with self._lock:
            return (key, index) in self._chunks

    def done(self, key: str, index: int) -> bool:
        """Held OR reaped — the pumps' "nothing left to fetch" check (a
        reaped chunk must not be re-pulled just to be re-freed)."""
        with self._lock:
            return (key, index) in self._chunks \
                or (key, index) in self._reaped

    def reaped(self, key: str, index: int) -> bool:
        with self._lock:
            return (key, index) in self._reaped

    def reap(self, key: str, index: int) -> int:
        """Free one chunk's bytes (returns how many; 0 when not held).
        The possession bit moves to the reaped set: progress stats keep
        counting it, the summary stops advertising it."""
        with self._lock:
            data = self._chunks.pop((key, index), None)
            if data is None:
                return 0
            self._reaped.add((key, index))
            self._bytes_reaped += len(data)
            self._version += 1
        _charge_ram(-len(data))
        return len(data)

    def unreap(self, key: str, index: int) -> None:
        """A local reader needs a reaped chunk after all: clear the flag
        so the acquisition path (origin/peer fetch) claims it again."""
        with self._lock:
            self._reaped.discard((key, index))

    def have(self, key: str) -> set[int]:
        with self._lock:
            return {i for (k, i) in self._chunks if k == key}

    def held(self) -> list[tuple[str, int]]:
        """Every chunk currently holding bytes (the reaper's scan set)."""
        with self._lock:
            return list(self._chunks)

    def version(self) -> int:
        with self._lock:
            return self._version

    def summary(self) -> dict:
        """Bounded, versioned possession advertisement: one bitmap per
        file (n/8 bytes hex), never the chunk list — a 13 GB manifest at
        8 MB chunks is a ~208-byte bitmap. ``have`` is what this host
        can SERVE right now; ``done`` additionally includes reaped
        chunks (landed once, bytes freed) — siblings gate their own
        reaps on ``done``, never ``have``, or the first host to reap a
        chunk would block every later host from ever reaping it."""
        with self._lock:
            return {
                "pull": self.pull_id,
                "host": self.host_id,
                "v": self._version,
                "files": {
                    k: {"n": n,
                        "have": _bitmap_hex(
                            {i for (fk, i) in self._chunks if fk == k}, n),
                        "done": _bitmap_hex(
                            {i for (fk, i) in self._chunks if fk == k}
                            | {i for (fk, i) in self._reaped if fk == k},
                            n)}
                    for k, n in self._files.items()
                },
            }

    def stats(self) -> dict:
        with self._lock:
            total = sum(self._files.values())
            return {
                "pull": self.pull_id, "host": self.host_id,
                "files": len(self._files), "chunks_total": total,
                # progress counts reaped chunks (they DID land; reaping
                # is a memory release, not lost work)
                "chunks_have": len(self._chunks) + len(self._reaped),
                "bytes_held": sum(len(b) for b in self._chunks.values()),
                "chunks_reaped": len(self._reaped),
                "bytes_reaped": self._bytes_reaped,
                "v": self._version,
            }

    def clear(self) -> None:
        with self._lock:
            held = sum(len(b) for b in self._chunks.values())
            self._chunks.clear()
            self._files.clear()
            self._reaped.clear()
            self._version += 1
        _charge_ram(-held)


# ----------------------------------------------------- process board registry
#
# The restore server and statusz resolve boards from here (keyed by
# "{pull_id}/{host_id}" so an in-process multi-host simulation — the
# bench, the chaos tests — can host N boards in one registry exactly the
# way N pod processes host one each).

_boards_lock = threading.Lock()
_boards: dict[str, ChunkBoard] = {}


def board_key(pull_id: str, host_id: str) -> str:
    return f"{pull_id}/{host_id}"


def register_board(board: ChunkBoard) -> None:
    with _boards_lock:
        _boards[board_key(board.pull_id, board.host_id)] = board


def unregister_board(board: ChunkBoard) -> None:
    with _boards_lock:
        key = board_key(board.pull_id, board.host_id)
        if _boards.get(key) is board:
            del _boards[key]


def board(pull_id: str, host_id: str) -> ChunkBoard | None:
    with _boards_lock:
        return _boards.get(board_key(pull_id, host_id))


def boards_snapshot() -> list[dict]:
    """Live swarm progress for ``/debug/statusz`` (read-only)."""
    with _boards_lock:
        boards = list(_boards.values())
    return [b.stats() for b in boards]
