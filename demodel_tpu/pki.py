"""CA lifecycle and per-host leaf-certificate minting.

Re-implements the reference PKI semantics (``cmd/demodel/init.go:26-154`` for
the root CA, ``cmd/demodel/start.go:27-165`` for leaf minting) on top of
``cryptography`` — this is control-plane work (once per install / once per
first-seen host), so Python is the right altitude; the C++ data plane only
*loads* the PEM files this module writes.

Reference semantics kept:
- load-or-create self-signed root CA; RSA (ref used 4095 bits — an off-by-one
  we fix to 4096) or ECDSA-P256 under ``DEMODEL_PROXY_CA_USE_ECDSA``
  (``init.go:66-70``);
- SubjectKeyId = SHA1 of the SPKI (``init.go:79-92``);
- validity 2 years 3 months, the mkcert convention (``init.go:94-99``);
- ``CA:TRUE`` with MaxPathLen 0 (``init.go:111-115``);
- PEM files in the XDG data dir as ``certificates/demodel-ca.{crt,pem}``
  with 0644/0600 modes (``init.go:32-38,135-143``);
- leaf certs: signed by the CA, serverAuth+clientAuth EKU, DNS SAN =
  hostname, same 2y3m validity (``start.go:71-87``), cached in-memory
  under a lock (``start.go:37-38,118-120`` — we close its benign TOCTOU
  with a double-check under the write lock);
- 128-bit random serial numbers (``main.go:49-54``).

Reference bug NOT reproduced (SURVEY.md §5): the ref attempts a trust-store
install of a pwd-relative file it never wrote (``init.go:145``) and panics the
first run; we install from the real written path and treat failure as a warning.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import secrets
import threading
from dataclasses import dataclass
from pathlib import Path

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, rsa
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

CA_CERT_NAME = "demodel-ca.crt"
CA_KEY_NAME = "demodel-ca.pem"


def _write_private(path: Path, data: bytes) -> None:
    """Create key files 0600 atomically (no world-readable write→chmod window;
    the reference passes the mode to os.WriteFile, ``init.go:139-143``)."""
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        os.write(fd, data)
    finally:
        os.close(fd)

#: mkcert-convention validity (reference ``init.go:94-99``).
VALIDITY = (2, 3)  # years, months

_ORG = "demodel-tpu development CA"


def _not_after(now: datetime.datetime) -> datetime.datetime:
    years, months = VALIDITY
    month = now.month + months
    year = now.year + years + (month - 1) // 12
    month = (month - 1) % 12 + 1
    day = min(now.day, 28)
    return now.replace(year=year, month=month, day=day)


def _new_key(use_ecdsa: bool):
    if use_ecdsa:
        return ec.generate_private_key(ec.SECP256R1())
    # Leafs don't need 4096 bits and minting cost is the per-host hot step
    # (the ref pays full-size keygen per first-seen host, ``start.go:51-55``);
    # the CA stays 4096.
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _serial() -> int:
    # 128-bit random serial (reference ``main.go:49-54``).
    return secrets.randbits(128)


@dataclass
class CA:
    cert: x509.Certificate
    key: object  # rsa or ec private key
    cert_pem: bytes
    key_pem: bytes


def ca_paths(data_dir: Path) -> tuple[Path, Path]:
    d = data_dir / "certificates"
    return d / CA_CERT_NAME, d / CA_KEY_NAME


def read_or_new_ca(data_dir: Path, use_ecdsa: bool = False) -> CA:
    """Load the root CA from ``data_dir`` or create+persist it.

    Mirrors ``readOrNewCA`` (``init.go:31-154``): files-exist early return,
    else keygen → self-sign → write PEMs (0644 cert / 0600 key).
    """
    cert_path, key_path = ca_paths(data_dir)
    if cert_path.exists() and key_path.exists():
        cert_pem = cert_path.read_bytes()
        key_pem = key_path.read_bytes()
        cert = x509.load_pem_x509_certificate(cert_pem)
        key = serialization.load_pem_private_key(key_pem, password=None)
        return CA(cert, key, cert_pem, key_pem)

    key = ec.generate_private_key(ec.SECP256R1()) if use_ecdsa else rsa.generate_private_key(
        public_exponent=65537, key_size=4096
    )
    now = datetime.datetime.now(datetime.timezone.utc) - datetime.timedelta(hours=1)
    # Per-instance unique CN (mkcert does the same with user@host): OpenSSL
    # resolves issuers BY SUBJECT, so two independent demodel CAs with an
    # identical DN would collide during chain building whenever both are
    # visible to one verifier (e.g. one installed in the OS trust store and
    # another presented in a handshake) — the wrong-keyed candidate can make
    # verification fail outright.
    import secrets

    name = x509.Name(
        [
            x509.NameAttribute(
                NameOID.COMMON_NAME, f"demodel-tpu CA {secrets.token_hex(4)}"),
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, _ORG),
        ]
    )
    ski = x509.SubjectKeyIdentifier.from_public_key(key.public_key())
    builder = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(_serial())
        .not_valid_before(now)
        .not_valid_after(_not_after(now))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=False,
                content_commitment=False,
                key_encipherment=False,
                data_encipherment=False,
                key_agreement=False,
                key_cert_sign=True,
                crl_sign=True,
                encipher_only=False,
                decipher_only=False,
            ),
            critical=True,
        )
        .add_extension(ski, critical=False)
    )
    cert = builder.sign(key, hashes.SHA256())

    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    cert_path.parent.mkdir(parents=True, exist_ok=True)
    cert_path.write_bytes(cert_pem)
    os.chmod(cert_path, 0o644)
    _write_private(key_path, key_pem)
    return CA(cert, key, cert_pem, key_pem)


class LeafMinter:
    """Per-host leaf certificate minting with an in-memory cache.

    The reference's ``CertStorage`` (``start.go:27-165``): first ``fetch``
    for a hostname mints a leaf signed by the root CA and caches it. We mint
    to PEM *files* (under ``work_dir``) because the C++ data plane consumes
    cert/key paths via ``SSL_CTX_use_certificate_chain_file``.
    """

    def __init__(self, ca: CA, work_dir: Path, use_ecdsa: bool = False):
        self.ca = ca
        self.work_dir = Path(work_dir)
        self.use_ecdsa = use_ecdsa
        self._lock = threading.Lock()
        self._cache: dict[str, tuple[str, str]] = {}
        #: per-host single-flight: concurrent fetches of ONE host mint once,
        #: while distinct hosts mint in parallel
        self._mint_locks: dict[str, threading.Lock] = {}

    def fetch(self, hostname: str) -> tuple[str, str]:
        """Return ``(cert_path, key_path)`` for ``hostname``, minting once.

        Unlike the ref (``start.go:118-120``) two threads cannot mint the
        same host concurrently — a per-host mint lock single-flights the
        mint. The mint itself (an RSA keygen taking whole seconds at the
        reference's 4095-bit default, plus PEM file writes) runs OUTSIDE
        the cache lock: holding the global lock across it serialized the
        first CONNECT of every distinct host behind one keygen
        (no-blocking-io-under-lock finding, PR 1).
        """
        with self._lock:
            hit = self._cache.get(hostname)
            if hit is not None:
                return hit
            mint_lock = self._mint_locks.setdefault(hostname,
                                                    threading.Lock())
        with mint_lock:
            # double-check: another thread may have minted while we waited
            with self._lock:
                hit = self._cache.get(hostname)
                if hit is not None:
                    return hit
            # demodel: allow(no-blocking-io-under-lock) — per-host
            # single-flight lock guarding exactly this mint; the global
            # cache lock is never held here
            paths = self._mint(hostname)
            with self._lock:
                self._cache[hostname] = paths
        return paths

    def _mint(self, hostname: str) -> tuple[str, str]:
        key = _new_key(self.use_ecdsa)
        now = datetime.datetime.now(datetime.timezone.utc) - datetime.timedelta(hours=1)
        san: list[x509.GeneralName]
        try:
            san = [x509.IPAddress(ipaddress.ip_address(hostname))]
        except ValueError:
            san = [x509.DNSName(hostname)]
        builder = (
            x509.CertificateBuilder()
            .subject_name(
                x509.Name(
                    [
                        x509.NameAttribute(NameOID.COMMON_NAME, hostname),
                        x509.NameAttribute(NameOID.ORGANIZATION_NAME, _ORG),
                    ]
                )
            )
            .issuer_name(self.ca.cert.subject)
            .public_key(key.public_key())
            .serial_number(_serial())
            .not_valid_before(now)
            .not_valid_after(_not_after(now))
            .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
            .add_extension(x509.SubjectAlternativeName(san), critical=False)
            .add_extension(
                x509.ExtendedKeyUsage(
                    [ExtendedKeyUsageOID.SERVER_AUTH, ExtendedKeyUsageOID.CLIENT_AUTH]
                ),
                critical=False,
            )
        )
        cert = builder.sign(self.ca.key, hashes.SHA256())

        d = self.work_dir / "leafs"
        d.mkdir(parents=True, exist_ok=True)
        safe = hostname.replace(":", "_").replace("/", "_")
        cert_path = d / f"{safe}.crt"
        key_path = d / f"{safe}.key"
        # Chain file: leaf + CA so clients can build the path.
        cert_path.write_bytes(
            cert.public_bytes(serialization.Encoding.PEM) + self.ca.cert_pem
        )
        _write_private(
            key_path,
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            ),
        )
        return str(cert_path), str(key_path)
