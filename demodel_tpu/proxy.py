"""Python control plane for the C++ MITM caching proxy.

Wires the native data plane (``native/proxy.cc``) to the Python-side PKI:
the C++ proxy calls back into :class:`~demodel_tpu.pki.LeafMinter` the first
time it sees a host, then caches the SSL_CTX natively. Mirrors the reference
``start()`` wiring (``cmd/demodel/start.go:167-216``).
"""

from __future__ import annotations

import ctypes
import json
import os
import threading

from demodel_tpu import native
from demodel_tpu.config import ProxyConfig
from demodel_tpu.utils.env import env_int

_MINT_CB = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,                 # host
    ctypes.POINTER(ctypes.c_char),   # cert path out
    ctypes.POINTER(ctypes.c_char),   # key path out
    ctypes.c_int,                    # buffer cap
)


class ProxyServer:
    """Owns a native proxy instance plus the CA/minter that feed it."""

    def __init__(
        self,
        cfg: ProxyConfig,
        upstream_ca: str | None = None,
        verbose: bool = True,
        io_timeout_sec: int = 75,
        max_body_mb: int = 64,
        session_threads: int | None = None,
        session_queue: int | None = None,
        reactor: bool | None = None,
        max_conns: int | None = None,
    ):
        self.cfg = cfg
        if upstream_ca is None:
            upstream_ca = cfg.upstream_ca
        self._lib = native.lib()
        self._setup_sigs()
        self._stop_evt = threading.Event()

        if cfg.no_mitm:
            # a pure tunnel/peer-serve node never mints leaves, so the PKI
            # stack (and its `cryptography` dependency) is not required —
            # peer/restore serving must work on dep-light hosts
            self.ca = None
            self._minter = None
            self._mint_cb = None
        else:
            from demodel_tpu import pki

            self.ca = pki.read_or_new_ca(cfg.data_dir, use_ecdsa=cfg.use_ecdsa)
            self._minter = pki.LeafMinter(self.ca, cfg.data_dir,
                                          use_ecdsa=cfg.use_ecdsa)

            def _mint(host: bytes, cert_out, key_out, cap: int) -> int:
                try:
                    cert, key = self._minter.fetch(host.decode())
                    cb = cert.encode() + b"\0"
                    kb = key.encode() + b"\0"
                    if len(cb) > cap or len(kb) > cap:
                        return -1
                    ctypes.memmove(cert_out, cb, len(cb))
                    ctypes.memmove(key_out, kb, len(kb))
                    return 0
                except Exception:  # noqa: BLE001 — crossing the C boundary
                    return -1

            # keep a reference: the native side holds this pointer for its
            # lifetime
            self._mint_cb = _MINT_CB(_mint)

        store_root = str(cfg.cache_dir / "proxy") if cfg.cache_enabled else ""
        self._h = self._lib.dm_proxy_new(
            cfg.host.encode(),
            cfg.port,
            1 if cfg.mitm_all else 0,
            1 if cfg.no_mitm else 0,
            ",".join(cfg.mitm_hosts).encode(),
            store_root.encode(),
            (upstream_ca or "").encode(),
            1 if cfg.cache_enabled else 0,
            ctypes.cast(self._mint_cb, ctypes.c_void_p)
            if self._mint_cb is not None else None,
            1 if verbose else 0,
            io_timeout_sec,
            env_int("DEMODEL_MAX_BODY_MB", max_body_mb),
            env_int("DEMODEL_CACHE_MAX_GB", 0) << 10,  # → MB; 0 = unbounded
            0 if os.environ.get("DEMODEL_RANGED_FILL", "").strip().lower()
            in ("0", "false", "no", "off") else 1,
            env_int("DEMODEL_FILL_MAX_MB", 512),
            env_int("DEMODEL_FILL_MIN_PCT", 5),
            env_int("DEMODEL_CHALLENGE_TTL_S", 86400),
            # bounded session executor: explicit value wins, 0 lets the
            # native side resolve DEMODEL_PROXY_THREADS / DEMODEL_PROXY_QUEUE
            # then fall back to the affinity-aware default (2×CPUs)
            session_threads if session_threads is not None else 0,
            session_queue if session_queue is not None else 0,
            # event-driven serve plane: None → -1 lets the native side
            # resolve DEMODEL_PROXY_REACTOR (on by default; "0" disables);
            # max_conns None → 0 resolves DEMODEL_PROXY_MAX_CONNS (4096)
            (-1 if reactor is None else (1 if reactor else 0)),
            max_conns if max_conns is not None else 0,
        )
        if not self._h:
            raise OSError("proxy allocation failed")

    def _setup_sigs(self) -> None:
        c = ctypes
        L = self._lib
        if getattr(L, "_proxy_sigs_done", False):
            return
        L.dm_proxy_new.argtypes = [
            c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_char_p, c.c_char_p,
            c.c_char_p, c.c_int, c.c_void_p, c.c_int, c.c_int, c.c_int64,
            c.c_int64, c.c_int, c.c_int64, c.c_int, c.c_int, c.c_int,
            c.c_int, c.c_int, c.c_int,
        ]
        L.dm_proxy_new.restype = c.c_void_p
        L.dm_proxy_start.argtypes = [c.c_void_p]
        L.dm_proxy_start.restype = c.c_int
        L.dm_proxy_port.argtypes = [c.c_void_p]
        L.dm_proxy_port.restype = c.c_int
        L.dm_proxy_stop.argtypes = [c.c_void_p]
        L.dm_proxy_stop.restype = None
        L.dm_proxy_free.argtypes = [c.c_void_p]
        L.dm_proxy_free.restype = None
        L.dm_proxy_metrics.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
        L.dm_proxy_metrics.restype = c.c_int
        L.dm_proxy_profile.argtypes = [
            c.c_void_p, c.c_int, c.c_int, c.c_int, c.c_char_p, c.c_int,
        ]
        L.dm_proxy_profile.restype = c.c_int
        L.dm_proxy_register_tensor.argtypes = [
            c.c_void_p, c.c_char_p, c.c_char_p, c.c_int64, c.c_int64,
        ]
        L.dm_proxy_register_tensor.restype = None
        L.dm_proxy_unregister_model.argtypes = [c.c_void_p, c.c_char_p]
        L.dm_proxy_unregister_model.restype = None
        L.dm_proxy_unregister_tensor.argtypes = [c.c_void_p, c.c_char_p]
        L.dm_proxy_unregister_tensor.restype = None
        L._proxy_sigs_done = True

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ProxyServer":
        rc = self._lib.dm_proxy_start(self._h)
        if rc != 0:
            raise OSError(-rc, "proxy start failed")
        return self

    @property
    def port(self) -> int:
        return self._lib.dm_proxy_port(self._h)

    @property
    def url(self) -> str:
        host = "127.0.0.1" if self.cfg.host in ("0.0.0.0", "") else self.cfg.host
        return f"http://{host}:{self.port}"

    def register_tensor(self, model: str, tensor: str, key: str,
                        start: int, nbytes: int) -> None:
        """Expose a tensor byte window on the native restore data plane
        (``GET /restore/{model}/tensor/{tensor}`` on the proxy port, range-
        aware, sendfile-served). The Python restore server registers its
        models here when attached — control plane in Python, bytes in C++."""
        self._lib.dm_proxy_register_tensor(
            self._h, f"{model}/{tensor}".encode(), key.encode(),
            start, nbytes)

    def unregister_model(self, model: str) -> None:
        """Drop every ``model/*`` entry from the native restore map and
        release its pins (full teardown). For re-registration use
        register_tensor for the new set (same-name entries replace
        atomically) + unregister_tensor for the stale names — a drop-all
        window would briefly 404 live fetches of kept tensors."""
        self._lib.dm_proxy_unregister_model(self._h, model.encode())

    def unregister_tensor(self, model: str, tensor: str) -> None:
        """Drop one tensor entry from the native restore map, releasing
        its pin — the per-entry half of a stale-tensor sweep."""
        self._lib.dm_proxy_unregister_tensor(
            self._h, f"{model}/{tensor}".encode())

    def metrics(self) -> dict:
        # dm_proxy_metrics returns the full JSON length; the per-route
        # histograms make the document variable-size, so grow and retry
        # when the first buffer truncates
        cap = 8192
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.dm_proxy_metrics(self._h, buf, cap)
            if n < cap:
                return json.loads(buf.value.decode())
            cap = n + 1

    def profile(self, seconds: float = 1.0, hz: int = 0,
                fmt: str = "json") -> dict | str | None:
        """Capture a native-plane profile window.

        Blocks for ``seconds`` (clamped to 5 by the native side) while the
        in-process sampler accumulates, then returns the delta as a dict
        (``fmt="json"``) or a Brendan-Gregg collapsed string
        (``fmt="collapsed"``). ``None`` means the profiler is disabled
        (``DEMODEL_OBS=0``) — the same contract as ``profiler.capture``.
        """
        collapsed = 1 if fmt == "collapsed" else 0
        # the native side bounds the document (top 256 stacks + rollup),
        # so 1 MB always suffices; the retry mirrors metrics() anyway in
        # case that bound ever moves
        cap = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.dm_proxy_profile(
                self._h, int(seconds * 1000), hz, collapsed, buf, cap)
            if n == 0:
                return None
            if n < cap:
                text = buf.value.decode()
                return text if collapsed else json.loads(text)
            cap = n + 1
            seconds = 0.0  # the window already happened; re-read cumulative

    def wait(self) -> None:
        self._stop_evt.wait()

    def stop(self) -> None:
        if self._h:
            self._lib.dm_proxy_stop(self._h)
            self._lib.dm_proxy_free(self._h)
            self._h = None
        self._stop_evt.set()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
