from demodel_tpu.registry.base import Fetcher, FileArtifact, PullReport
from demodel_tpu.registry.hf import HFRegistry
from demodel_tpu.registry.ollama import OllamaRegistry

__all__ = ["Fetcher", "FileArtifact", "PullReport", "HFRegistry",
           "OllamaRegistry"]
