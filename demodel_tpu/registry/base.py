"""Shared streaming fetch machinery for registry adapters.

The reference has no native pull client — it relies on foreign clients
(huggingface-cli, Ollama, …) pulling *through* the proxy (``README.md:14-21``).
The rebuild keeps that interception path (see ``demodel_tpu.proxy``) and adds
this first-party client so ``demodel-tpu pull`` can populate the same
content-addressed store directly and feed the TPU sink, with chunk-level
resume the reference never had (SURVEY.md §5 "Checkpoint / resume").
"""

from __future__ import annotations


import errno
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import requests

from demodel_tpu.store import Store, key_for_uri
from demodel_tpu.utils import trace
from demodel_tpu.utils.env import env_int
from demodel_tpu.utils.faults import RetryPolicy, request_with_retry
from demodel_tpu.utils.logging import get_logger

log = get_logger("registry")

CHUNK = 1 << 20


def _registry_timeout() -> int:
    """Per-request timeout for upstream-registry metadata calls
    (``DEMODEL_REGISTRY_TIMEOUT``, seconds). Retries ride the wire
    :class:`RetryPolicy` on top of this."""
    return env_int("DEMODEL_REGISTRY_TIMEOUT", 60, minimum=1)


@dataclass
class FileArtifact:
    name: str
    uri: str            # canonical (pre-redirect) URI — store key derives from it
    key: str
    size: int
    sha256: str
    media_type: str = ""
    etag: str = ""
    from_cache: bool = False
    from_peer: bool = False
    resumed_from: int = 0
    secs: float = 0.0
    #: host landing buffer (memory-first peer fetch) — consumed by the HBM
    #: sink; never serialized into reports
    buffer: object = None
    #: True when the buffer's bytes were charged against the delivery's
    #: shared ByteBudget at allocation (the sink releases them on landing)
    budget_charged: bool = False


@dataclass
class PullReport:
    source: str
    name: str
    revision: str
    files: list[FileArtifact] = field(default_factory=list)
    secs: float = 0.0

    @property
    def total_bytes(self) -> int:
        return sum(f.size for f in self.files)

    def to_dict(self) -> dict:
        return {
            "source": self.source,
            "name": self.name,
            "revision": self.revision,
            "total_bytes": self.total_bytes,
            "secs": round(self.secs, 3),
            "files": [{k: v for k, v in vars(f).items()
                       if k not in ("buffer", "budget_charged")}
                      for f in self.files],
        }


class Fetcher:
    """requests-based streaming downloader writing through the Store.

    Sessions are per-thread so registry adapters can fetch shards
    concurrently (``requests.Session`` is not thread-safe)."""

    def __init__(self, store: Store, ca: str | None = None,
                 proxies: dict | None = None, headers: dict | None = None,
                 peers=None, memory_sink: bool = False, buffer_budget=None):
        self.store = store
        # per-request verify (not Session.verify): a REQUESTS_CA_BUNDLE /
        # CURL_CA_BUNDLE env var silently overrides the session attribute
        self.verify = ca if ca else True
        self.peers = peers  # Optional[demodel_tpu.parallel.peer.PeerSet]
        #: memory-first delivery: peer bytes land in a host buffer handed
        #: straight to the HBM sink; the cache copy commits off the
        #: delivery critical path (join via flush_writes)
        self.memory_sink = memory_sink
        #: demodel_tpu.sink.streaming.ByteBudget shared with the sink —
        #: landing-buffer allocation blocks HERE, so N fetch workers can
        #: never pin N full shards (the r3 scale-test finding)
        self.buffer_budget = buffer_budget
        self._proxies = dict(proxies or {})
        self._headers = dict(headers or {})
        #: one wire policy per Fetcher (constructed per pull, so env
        #: overrides land); upstream registries get retries but NO
        #: breakers — there is exactly one of each, nothing to rotate to
        self._policy = RetryPolicy()
        self._tls = threading.local()
        self._commit_lock = threading.Lock()
        self._commit_pool: ThreadPoolExecutor | None = None
        self._commit_futs: list = []
        self._deferred_commits: list[tuple] = []
        #: bytes of landing buffers held by pending/in-flight commits
        #: (incremented at submit, released as each commit completes)
        self._backlog_bytes = 0
        #: ``[(key, "ExcType: msg")]`` for cache commits that failed —
        #: populated by the commit workers, returned by :meth:`flush_writes`
        #: so callers can drop those keys from durable manifests
        self.commit_failures: list[tuple[str, str]] = []
        #: subset of :attr:`commit_failures` where the re-hash found the
        #: delivered bytes CORRUPT (EBADMSG) — callers must treat the
        #: placement built from those buffers as poisoned
        self.integrity_failures: list[tuple[str, str]] = []

    @property
    def session(self) -> requests.Session:
        s = getattr(self._tls, "session", None)
        if s is None:
            s = requests.Session()
            s.proxies.update(self._proxies)
            s.headers.update(self._headers)
            self._tls.session = s
        return s

    def get_json(self, url: str) -> dict:
        r = request_with_retry(
            self.session, "GET", url, policy=self._policy,
            timeout=_registry_timeout(), verify=self.verify,
            what=f"registry GET {url}")
        return r.json()

    @staticmethod
    def _mode_env(var: str, truthy: tuple, falsy: tuple) -> bool | None:
        """Parse a mode knob; boolean spellings accepted, unrecognized
        non-empty values warn and yield None (degrade-not-crash, matching
        ``utils/env.py``'s contract)."""
        env = os.environ.get(var, "").strip().lower()
        if not env:
            return None
        if env in truthy or env in ("1", "true", "yes", "on"):
            return True
        if env in falsy or env in ("0", "false", "no", "off"):
            return False
        log.warning("%s=%r not recognized (want %s/%s); using default",
                    var, env, truthy[0], falsy[0])
        return None

    @staticmethod
    def _verify_eager() -> bool:
        """Whether memory-first peer bytes are sha256-verified inline
        (before delivery) or optimistically at the background cache commit.
        Default couples to :meth:`_commit_eager`: with spare cores the
        inline hash overlaps the transfer and fails early; on a starved
        host it would serialize with the transfer, so verification rides
        the commit and surfaces via ``Placement.finalize``."""
        mode = Fetcher._mode_env("DEMODEL_PEER_VERIFY",
                                 ("eager", "inline"),
                                 ("commit", "lazy", "deferred"))
        return mode if mode is not None else Fetcher._commit_eager()

    @staticmethod
    def _commit_eager() -> bool:
        """Whether cache commits overlap the pull (spare cores) or defer to
        ``flush_writes`` (a starved host must not let disk writes + digest
        re-verification contend with fetch and device dispatch — measured
        as the bulk of the r02 bench regression on a 1-core host)."""
        mode = Fetcher._mode_env("DEMODEL_CACHE_COMMIT",
                                 ("eager", "overlap"),
                                 ("deferred", "lazy"))
        from demodel_tpu.utils.env import available_cpus

        # affinity-aware: a container pinned to 1 CPU on a 64-core host
        # must defer, same as a genuinely 1-core box
        return mode if mode is not None else available_cpus() >= 4

    @staticmethod
    def _commit_backlog_budget() -> int:
        """Bytes of landing buffers the pending-commit backlog may pin
        (``DEMODEL_COMMIT_BACKLOG_MB``). Pending commits hold a reference to
        the full file buffer; without a bound, a 15-shard 70B pull would pin
        the whole checkpoint in host RAM regardless of the sink's own
        budget."""
        return env_int("DEMODEL_COMMIT_BACKLOG_MB", 2048, minimum=1) << 20

    def _commit_buffer_async(self, key: str, buf, peer_meta: dict,
                             digest: str) -> None:
        """Persist a landing buffer into the store off the critical path
        (deferred to ``flush_writes`` on starved hosts, a 2-worker pool
        otherwise). If the backlog would pin more than the byte budget, the
        calling fetch worker drains the oldest job inline — fetch throttles
        to disk instead of accumulating unbounded RAM."""
        job = (key, buf, dict(peer_meta), digest)
        budget = self._commit_backlog_budget()
        if not self._commit_eager():
            drain = []
            with self._commit_lock:
                self._deferred_commits.append(job)
                self._backlog_bytes += len(buf)
                projected = self._backlog_bytes
                while projected > budget and len(self._deferred_commits) > 1:
                    oldest = self._deferred_commits.pop(0)
                    projected -= len(oldest[1])
                    drain.append(oldest)
            for j in drain:  # _commit_one releases each job's bytes
                self._commit_one(j)
            return
        with self._commit_lock:
            if self._commit_pool is None:
                # a small shared pool: N uncapped threads would pin N full
                # landing buffers and thrash the disk (ADVICE r2)
                self._commit_pool = ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="cache-commit")
            self._backlog_bytes += len(buf)
            self._commit_futs.append(
                self._commit_pool.submit(self._commit_one, job))
        while True:
            # disk lagging the network: block this fetch worker on the
            # oldest LIVE commit until the backlog fits the budget, so
            # queued futures can't pin the whole model (done futures have
            # already released their bytes — prune, don't wait on them)
            with self._commit_lock:
                self._commit_futs = [f for f in self._commit_futs
                                     if not f.done()]
                over = self._backlog_bytes > budget
                oldest_fut = self._commit_futs[0] if self._commit_futs else None
            if not over or oldest_fut is None:
                return
            oldest_fut.result()

    def _commit_one(self, job: tuple) -> None:
        key, buf, peer_meta, digest = job
        try:
            try:
                w = self.store.begin_ranged(key, len(buf))
                try:
                    w.pwrite(buf, 0)
                    w.commit(peer_meta, expected_digest=digest or None)
                except BaseException:
                    w.abort()
                    raise
            except OSError as e:
                if e.errno != errno.EBADMSG and digest:
                    # the commit died BEFORE its re-hash could verify the
                    # delivered bytes (e.g. ENOSPC) — under optimistic
                    # verification that hash is the only integrity check, so
                    # run it directly on the buffer before reporting a
                    # plain cache failure
                    import hashlib

                    got = hashlib.sha256(buf).hexdigest()
                    if got != digest:
                        raise OSError(
                            errno.EBADMSG,
                            f"delivered bytes hash {got}, expected {digest} "
                            f"(commit also failed: {e})") from e
                raise
        except BaseException as e:  # noqa: BLE001 — recorded, never escapes
            # cache write failure must not fail the delivery — the bytes
            # are already on device; the store just stays cold for this key.
            # EBADMSG is different: the re-hash proved the DELIVERED bytes
            # corrupt (optimistic verify) — record it so flush_writes /
            # finalize can poison the placement.
            entry = (key, f"{type(e).__name__}: {e}")
            with self._commit_lock:
                self.commit_failures.append(entry)
                if isinstance(e, OSError) and e.errno == errno.EBADMSG:
                    self.integrity_failures.append(entry)
            log.warning("background cache commit of %s failed: %s", key, e)
        finally:
            with self._commit_lock:
                self._backlog_bytes -= len(buf)

    def flush_writes(self, timeout: float | None = None) -> list[tuple[str, str]]:
        """Run deferred commits and join in-flight ones (store fully
        populated on return). Returns ``[(key, error)]`` for commits that
        failed — callers persisting manifests should omit those keys.

        On ``timeout`` the un-joined futures stay queued (a later flush can
        still join them — required before the store may be closed)."""
        with self._commit_lock:
            deferred, self._deferred_commits = self._deferred_commits, []
            futs = list(self._commit_futs)
        for job in deferred:
            self._commit_one(job)
        joined = []
        try:
            for f in futs:
                f.result(timeout)
                joined.append(f)
        finally:
            with self._commit_lock:
                self._commit_futs = [f for f in self._commit_futs
                                     if f not in joined]
        with self._commit_lock:
            return list(self.commit_failures)

    def probe_lfs_digest(self, url: str) -> str | None:
        """HEAD ``url`` (no redirect follow) and return the LFS blob sha256
        from ``X-Linked-Etag`` when present (the HF Hub convention for
        ``/resolve`` of an LFS file). One cheap round-trip that enables
        content-address dedup before any bytes move."""
        try:
            r = request_with_retry(
                self.session, "HEAD", url, policy=self._policy,
                timeout=min(30, _registry_timeout()), allow_redirects=False,
                verify=self.verify, check_status=False,
                what="LFS digest probe")
        except requests.RequestException:
            return None
        etag = (r.headers.get("X-Linked-Etag") or "").strip('"')
        if len(etag) == 64 and all(c in "0123456789abcdef" for c in etag):
            return etag
        return None

    def _try_upstream_parallel(self, url, name, expected_digest, media_type,
                               extra_headers, t0):
        """Large known-size upstream files fan out over N native TLS range
        connections (config-4-shaped cold pulls). Returns a FileArtifact or
        None to fall back to the single-stream requests path. Never used
        through an HTTP proxy (the native path speaks to the origin) or for
        credentialed requests (Authorization wouldn't be forwarded)."""
        import ctypes
        import json as _json
        from urllib.parse import urlsplit

        streams = _upstream_streams()
        min_bytes = env_int("DEMODEL_UPSTREAM_PARALLEL_MIN_MB", 64,
                            minimum=1) << 20
        if streams <= 1 or self._proxies or extra_headers:
            return None
        session_auth = "Authorization" in self.session.headers
        try:
            h = request_with_retry(
                self.session, "HEAD", url, policy=self._policy,
                timeout=min(30, _registry_timeout()), allow_redirects=True,
                verify=self.verify, check_status=False,
                what="upstream size probe")
        except requests.RequestException:
            return None
        size = int(h.headers.get("Content-Length") or 0)
        if (not h.ok or size < min_bytes
                or "bytes" not in h.headers.get("Accept-Ranges", "")):
            return None
        parts = urlsplit(h.url)
        if parts.scheme not in ("http", "https") or not parts.hostname:
            return None
        if session_auth and not (h.url != url and parts.query):
            # ADVICE r3 low: session-level Authorization (gated-repo HF
            # token) never enters the native path — it forwards no auth.
            # Proceed only when the HEAD redirected to a signed URL
            # (query-string credentials); a same-auth origin URL would
            # just 401 across N wasted TLS connects. NB a presigned URL
            # can still be bound to the HEAD method — the native fetch
            # degrades to single-stream on the first non-206 in that case.
            return None
        port = parts.port or (443 if parts.scheme == "https" else 80)
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        ca = self.verify if isinstance(self.verify, str) else ""
        key = key_for_uri(url)
        meta = {
            "uri": url, "name": name, "size": size,
            "sha256": expected_digest or "", "media_type": media_type,
            "final_url": h.url,
            "headers": {"content-type": h.headers.get("Content-Type", "")},
        }
        errbuf = ctypes.create_string_buffer(512)
        from demodel_tpu import native

        n = native.lib().dm_upstream_fetch_parallel(
            self.store._h,  # noqa: SLF001 — data-plane handoff
            parts.hostname.encode(), port,
            1 if parts.scheme == "https" else 0, ca.encode(), path.encode(),
            key.encode(), size, streams, (expected_digest or "").encode(),
            _json.dumps(meta).encode(), errbuf, 512)
        if n != size:
            log.debug("native upstream parallel fetch of %s failed (%s); "
                      "using single-stream", name,
                      errbuf.value.decode(errors="replace"))
            return None
        dt = time.perf_counter() - t0
        log.info("fetched %s: %d bytes upstream over %d streams in %.2fs",
                 name, size, streams, dt)
        stored = self.store.meta(key) or {}
        return FileArtifact(
            name=name, uri=url, key=key, size=size,
            sha256=stored.get("sha256", expected_digest or ""),
            media_type=media_type, etag=h.headers.get("ETag", "").strip('\'"'),
            secs=dt,
        )

    def fetch(
        self,
        url: str,
        name: str,
        expected_digest: str | None = None,
        media_type: str = "",
        extra_headers: dict | None = None,
    ) -> FileArtifact:
        """Stream ``url`` into the store under its URI key.

        - cache hit → served locally, zero network;
        - partial present → resumed with a Range request (falls back to a
          full restart when the server ignores the range);
        - ``expected_digest`` (hex sha256) verified against the streamed
          bytes; mismatch removes the entry and raises;
        - transport failures (resets, timeouts, 429/5xx, truncation)
          retry under the wire :class:`RetryPolicy`, each attempt resuming
          from the kept partial — digest mismatches and other 4xx never
          retry.
        """
        from demodel_tpu import tier
        with trace.span("registry-fetch", file=name) as sp:
            # single-flight admission on the registry miss edge: N
            # concurrent fetches of one key cost one upstream transfer —
            # the leader runs the retried fetch, waiters re-run
            # _fetch_once afterwards (a cache hit, zero network). The
            # ``origin:`` prefix keeps these flights apart from the tier
            # read path's watermark flights on the same registry.
            art = tier.shared(self.store).flights.do(
                "origin:" + key_for_uri(url),
                lambda: self._policy.call(
                    lambda: self._fetch_once(url, name, expected_digest,
                                             media_type, extra_headers),
                    what=f"fetch {name} "
                         "(each retry resumes the kept partial)"))
            if art is None:  # waiter — the leader landed it
                art = self._fetch_once(url, name, expected_digest,
                                       media_type, extra_headers)
            sp.set_attr("bytes", art.size)
            sp.set_attr("from_peer", art.from_peer)
            sp.set_attr("from_cache", art.from_cache)
            return art

    def _fetch_once(
        self,
        url: str,
        name: str,
        expected_digest: str | None = None,
        media_type: str = "",
        extra_headers: dict | None = None,
    ) -> FileArtifact:
        key = key_for_uri(url)
        t0 = time.perf_counter()
        from_peer = False
        if (not self.store.has(key) and expected_digest
                and self.store.has_digest(expected_digest)):
            # content-address hit: the same bytes are already local under a
            # different cache key (e.g. the MITM proxy cached them under the
            # post-redirect CDN URL) — publish a hardlink, zero transfer
            try:
                self.store.materialize(key, expected_digest, {
                    "uri": url, "name": name, "sha256": expected_digest,
                    "media_type": media_type,
                })
                log.info("dedup %s: materialized from local digest %s", name,
                         expected_digest[:12])
            except OSError as e:
                # benign race: the last key holding that digest was removed
                # between has_digest and link — fall through to peer/upstream
                log.debug("dedup %s failed (%s); fetching normally", name, e)
        if (not self.store.has(key) and self.peers is not None
                and self.memory_sink):
            got = self.peers.fetch_to_memory(key, expected_digest=expected_digest,
                                             eager_verify=self._verify_eager(),
                                             budget=self.buffer_budget)
            if got is not None:
                buf, peer_meta = got
                digest = expected_digest or peer_meta.get("sha256", "")
                self._commit_buffer_async(key, buf, peer_meta, digest)
                log.info("fetched %s: %d bytes from peer into memory in %.2fs",
                         name, len(buf), time.perf_counter() - t0)
                return FileArtifact(
                    name=name, uri=url, key=key, size=len(buf), sha256=digest,
                    media_type=media_type, etag=peer_meta.get("etag", ""),
                    from_peer=True, secs=time.perf_counter() - t0, buffer=buf,
                    budget_charged=self.buffer_budget is not None,
                )
        if not self.store.has(key) and self.peers is not None:
            # DCN-first: a pod peer that already holds the bytes beats the
            # upstream registry (README.md:5-10 made first-class)
            from_peer = self.peers.fetch_into(self.store, key,
                                              expected_digest=expected_digest)
        meta = self.store.meta(key) if self.store.has(key) else None
        if meta is not None:
            if expected_digest and meta.get("sha256") != expected_digest:
                log.warning("cached %s digest mismatch; refetching", name)
                self.store.remove(key)
            else:
                return FileArtifact(
                    name=name, uri=url, key=key, size=meta.get("size", self.store.size(key)),
                    sha256=meta.get("sha256", ""), media_type=media_type,
                    etag=meta.get("etag", ""), from_cache=not from_peer,
                    from_peer=from_peer, secs=time.perf_counter() - t0,
                )

        if self.store.partial_size(key) == 0:
            art = self._try_upstream_parallel(url, name, expected_digest,
                                              media_type, extra_headers, t0)
            if art is not None:
                return art

        resumed_from = 0
        partial = self.store.partial_size(key)
        headers = dict(extra_headers or {})
        if partial > 0:
            headers["Range"] = f"bytes={partial}-"

        r = self.session.get(url, headers=headers, stream=True, timeout=300,
                             allow_redirects=True, verify=self.verify)
        if partial > 0 and r.status_code == 416:
            # partial covers the whole object (e.g. crash between last byte
            # and commit) — the range is unsatisfiable; restart clean
            r.close()
            r = self.session.get(url, stream=True, timeout=300,
                                 allow_redirects=True, verify=self.verify)
            partial = 0
        try:
            if partial > 0 and r.status_code == 206:
                w = self.store.begin(key, resume=True)
                resumed_from = partial
            else:
                r.raise_for_status()
                w = self.store.begin(key, resume=False)
            try:
                for chunk in r.iter_content(CHUNK):
                    if chunk:
                        w.append(chunk)
                digest = w.digest()
                if expected_digest and digest != expected_digest:
                    w.abort(keep_partial=False)
                    raise IOError(
                        f"digest mismatch for {name}: got {digest}, want {expected_digest}"
                    )
                etag = (r.headers.get("ETag") or "").strip('"')
                size = w.offset
                w.commit(
                    {
                        "uri": url,
                        "name": name,
                        "size": size,
                        "sha256": digest,
                        "etag": etag,
                        "media_type": media_type,
                        "final_url": r.url,
                        "headers": {
                            "content-type": r.headers.get("Content-Type", ""),
                            "content-encoding": r.headers.get("Content-Encoding", ""),
                        },
                    }
                )
            except BaseException:
                # keep bytes for resume on transport errors; digest mismatch
                # already dropped them above
                if w._open:  # noqa: SLF001 — writer state check
                    w.abort(keep_partial=True)
                raise
        finally:
            r.close()
        dt = time.perf_counter() - t0
        log.info("fetched %s: %d bytes in %.2fs (resumed_from=%d)", name, size, dt,
                 resumed_from)
        return FileArtifact(
            name=name, uri=url, key=key, size=size, sha256=digest,
            media_type=media_type, etag=etag, resumed_from=resumed_from, secs=dt,
        )


def _upstream_streams() -> int:
    """Range connections per large upstream fetch (``DEMODEL_UPSTREAM_STREAMS``).

    The reference's clients stream one socket per file; big-file cold pulls
    from a CDN rarely fill the link that way (VERDICT r2 weak #6) — the
    native slice fan-out multiplies the in-flight window like the peer path
    does. 1 disables the native upstream path entirely."""
    return env_int("DEMODEL_UPSTREAM_STREAMS", 4, minimum=1)


def fetch_workers() -> int:
    """Concurrent shard fetches per pull (``DEMODEL_FETCH_WORKERS``).

    The reference's clients pull shards one at a time through the proxy;
    first-party pulls overlap transfers so a multi-shard checkpoint saturates
    the link (and a warm peer's serving threads) instead of round-tripping
    per file."""
    return env_int("DEMODEL_FETCH_WORKERS", 8, minimum=1)


def parallel_fetch(jobs: list, fn) -> list:
    """Run ``fn(job)`` over a thread pool, preserving job order.

    Any failure cancels nothing already in flight (their partials stay
    resumable) but re-raises the first error after all workers settle."""
    if len(jobs) <= 1 or fetch_workers() == 1:
        return [fn(j) for j in jobs]
    # trace.wrap PER JOB: worker threads don't inherit contextvars, and a
    # contextvars.Context can only be entered by one thread at a time —
    # one shared wrapped fn across the pool would raise "cannot enter
    # context" on the first concurrent pair (identity when tracing is off)
    with ThreadPoolExecutor(max_workers=min(fetch_workers(), len(jobs))) as ex:
        futs = [ex.submit(trace.wrap(fn), j) for j in jobs]
        return [f.result() for f in futs]
