"""HuggingFace Hub adapter.

Covers the HF flows the reference's client matrix exercises through the proxy
(``README.md:14-21``: huggingface-cli, transformers via ``HF_ENDPOINT``,
transformers.js): the Hub REST API (``/api/models/{repo}/revision/{rev}``),
the ``/{repo}/resolve/{rev}/{file}`` fetch path with its 302-to-CDN redirect
for LFS blobs, and the ETag/X-Repo-Commit metadata convention. Artifacts are
typed (safetensors index parsed) rather than opaque bodies — SURVEY.md §7
layer 3.
"""

from __future__ import annotations

import fnmatch
import time

from demodel_tpu.registry.base import Fetcher, FileArtifact, PullReport, parallel_fetch
from demodel_tpu.store import Store, key_for_uri
from demodel_tpu.utils.logging import get_logger

log = get_logger("hf")

DEFAULT_ENDPOINT = "https://huggingface.co"

#: File classes huggingface-cli pulls for a model snapshot; weights +
#: tokenizer + configs. Binary-format auxiliaries excluded by default.
DEFAULT_PATTERNS = (
    "*.safetensors", "*.safetensors.index.json", "*.json", "*.txt",
    "*.model", "tokenizer*", "*.gguf",
)

#: File classes a dataset snapshot carries (``datasets/`` repos): data
#: shards plus loading metadata.
DATASET_PATTERNS = (
    "*.parquet", "*.arrow", "*.csv", "*.jsonl", "*.json", "*.txt",
    "README.md", "dataset_infos.json",
)


class HFRegistry:
    def __init__(
        self,
        store: Store,
        endpoint: str = DEFAULT_ENDPOINT,
        token: str | None = None,
        ca: str | None = None,
        proxies: dict | None = None,
        peers=None,
        memory_sink: bool = False,
        buffer_budget=None,
    ):
        self.endpoint = endpoint.rstrip("/")
        headers = {"User-Agent": "demodel-tpu/0.1"}
        if token:
            headers["Authorization"] = f"Bearer {token}"
        self.fetcher = Fetcher(store, ca=ca, proxies=proxies, headers=headers,
                               peers=peers, memory_sink=memory_sink,
                               buffer_budget=buffer_budget)

    # -- API ------------------------------------------------------------
    def repo_info(self, repo_id: str, revision: str = "main") -> dict:
        """``GET /api/models/{repo}/revision/{rev}`` → repo JSON (sha,
        siblings[].rfilename, …). Dataset repos (the reference's first
        line promises "models and datasets", ``README.md:3``) live under
        a distinct namespace — ``/api/datasets/{repo}/revision/{rev}``
        and ``/datasets/{repo}/resolve/...`` — selected here by the
        ``datasets/`` repo-id prefix, mirroring the Hub's URL shape."""
        if repo_id.startswith("datasets/"):
            api = f"{self.endpoint}/api/{repo_id}/revision/{revision}"
        else:
            api = f"{self.endpoint}/api/models/{repo_id}/revision/{revision}"
        return self.fetcher.get_json(api)

    def list_files(self, repo_id: str, revision: str = "main") -> list[str]:
        info = self.repo_info(repo_id, revision)
        return [s["rfilename"] for s in info.get("siblings", [])]

    def resolve_url(self, repo_id: str, revision: str, filename: str) -> str:
        return f"{self.endpoint}/{repo_id}/resolve/{revision}/{filename}"

    # -- pulls ----------------------------------------------------------
    #: extensions stored as LFS blobs on the Hub — a HEAD of their resolve
    #: URL yields the blob sha256 (X-Linked-Etag) before any bytes move
    LFS_SUFFIXES = (".safetensors", ".gguf", ".bin", ".pt", ".onnx", ".h5",
                    ".parquet", ".arrow")

    def fetch_file(self, repo_id: str, revision: str, filename: str) -> FileArtifact:
        """Fetch one file via the resolve path (redirects followed; LFS
        blobs land via their CDN URL, stored under the canonical resolve
        URI so re-pulls and peers key consistently).

        For LFS files a digest probe runs first so bytes already held
        locally under another key (MITM'd CDN URL) or on a peer are reused
        by content address instead of re-transferred."""
        url = self.resolve_url(repo_id, revision, filename)
        expected = None
        if filename.endswith(self.LFS_SUFFIXES) and not self.fetcher.store.has(
            key_for_uri(url)
        ):
            expected = self.fetcher.probe_lfs_digest(url)
        return self.fetcher.fetch(url, name=filename, expected_digest=expected)

    def pull(
        self,
        repo_id: str,
        revision: str = "main",
        allow_patterns: tuple[str, ...] | None = None,
        on_file=None,
    ) -> PullReport:
        """Pull a snapshot. ``on_file(artifact)`` fires from the fetch
        worker as each file completes — the streaming-sink hook.
        ``allow_patterns`` defaults per namespace: model file classes, or
        dataset shards/metadata for ``datasets/`` repos."""
        if allow_patterns is None:
            allow_patterns = (DATASET_PATTERNS
                              if repo_id.startswith("datasets/")
                              else DEFAULT_PATTERNS)
        t0 = time.perf_counter()
        info = self.repo_info(repo_id, revision)
        commit = info.get("sha", revision)
        files = [s["rfilename"] for s in info.get("siblings", [])]
        wanted = [
            f for f in files
            if any(fnmatch.fnmatch(f, p) for p in allow_patterns)
        ]
        log.info("pulling %s@%s: %d/%d files", repo_id, revision, len(wanted), len(files))
        report = PullReport(source="hf", name=repo_id, revision=commit)
        # pin to the resolved commit so the snapshot is immutable; shards
        # fetch concurrently (base.parallel_fetch), report order preserved
        def fetch_one(f):
            art = self.fetch_file(repo_id, commit, f)
            if on_file is not None:
                on_file(art)
            return art

        report.files = parallel_fetch(wanted, fetch_one)
        report.secs = time.perf_counter() - t0
        return report
