"""Ollama / Docker-registry-v2 adapter.

The reference's canonical client flow (``CONTRIBUTING.md:39-51``):
``ollama pull`` speaks registry-v2 — manifest at
``/v2/{name}/manifests/{tag}`` (golden schema ``CONTRIBUTING.md:128-153``:
schemaVersion 2, ``application/vnd.ollama.image.*`` layer mediaTypes,
sha256 digests), blobs by digest at ``/v2/{name}/blobs/{digest}``. This
first-party client walks the same protocol into the content-addressed
store, digest-verifying every layer.
"""

from __future__ import annotations

import json
import time

from demodel_tpu.registry.base import Fetcher, PullReport, parallel_fetch
from demodel_tpu.store import Store
from demodel_tpu.utils.logging import get_logger

log = get_logger("ollama")

DEFAULT_ENDPOINT = "https://registry.ollama.ai"


def normalize_name(name_tag: str) -> tuple[str, str]:
    """Ollama name sugar → (repository, tag): bare names live under
    ``library/`` and default to ``:latest`` — ``llama3:8b`` →
    ``("library/llama3", "8b")``; ``user/model`` → ``("user/model",
    "latest")``."""
    name, _, tag = name_tag.partition(":")
    if "/" not in name:
        name = f"library/{name}"
    return name, tag or "latest"


class OllamaRegistry:
    def __init__(
        self,
        store: Store,
        endpoint: str = DEFAULT_ENDPOINT,
        ca: str | None = None,
        proxies: dict | None = None,
        peers=None,
        memory_sink: bool = False,
        buffer_budget=None,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.fetcher = Fetcher(
            store, ca=ca, proxies=proxies,
            headers={"User-Agent": "demodel-tpu/0.1"},
            peers=peers, memory_sink=memory_sink,
            buffer_budget=buffer_budget,
        )

    # -- registry-v2 URL shapes -----------------------------------------
    def manifest_url(self, name: str, tag: str) -> str:
        return f"{self.endpoint}/v2/{name}/manifests/{tag}"

    def blob_url(self, name: str, digest: str) -> str:
        return f"{self.endpoint}/v2/{name}/blobs/{digest}"
    def manifest(self, name: str, tag: str = "latest") -> dict:
        name, tag = normalize_name(f"{name}:{tag}" if ":" not in name else name)
        return self.fetcher.get_json(self.manifest_url(name, tag))

    def pull(self, name_tag: str, on_file=None) -> PullReport:
        """Pull manifest + config + all layers, digest-verifying each.
        ``on_file(artifact)`` fires per completed blob (streaming sink)."""
        t0 = time.perf_counter()
        name, tag = normalize_name(name_tag)
        # the manifest itself goes through the cache too; a memory-first
        # fetch returns the bytes in the artifact's landing buffer (the
        # store commit is asynchronous — reading back by key would race it)
        m_art = self.fetcher.fetch(self.manifest_url(name, tag), name=f"{name}:{tag}")
        if m_art.buffer is not None:
            manifest = json.loads(bytes(m_art.buffer).decode())
        else:
            manifest = json.loads(b"".join(self.fetcher.store.stream(m_art.key)).decode())
        if manifest.get("schemaVersion") != 2:
            raise ValueError(f"unsupported manifest schemaVersion: {manifest.get('schemaVersion')}")

        report = PullReport(source="ollama", name=name, revision=tag)
        report.files.append(m_art)
        blobs = []
        if "config" in manifest:
            blobs.append(manifest["config"])
        blobs.extend(manifest.get("layers", []))
        def fetch_blob(blob):
            digest = blob["digest"]
            algo, _, hexd = digest.partition(":")
            if algo != "sha256":
                raise ValueError(f"unsupported digest algorithm {algo}")
            art = self.fetcher.fetch(
                self.blob_url(name, digest),
                name=digest,
                expected_digest=hexd,
                media_type=blob.get("mediaType", ""),
            )
            if "size" in blob and art.size != blob["size"]:
                raise IOError(
                    f"size mismatch for {digest}: got {art.size}, want {blob['size']}"
                )
            if on_file is not None:
                on_file(art)
            return art

        # layers fetch concurrently (GGUF blob + license + params etc.);
        # dedup by digest first — a repeated layer would race two writers on
        # one store key, and the second would fail "writer already active"
        unique: dict[str, dict] = {}
        for blob in blobs:
            unique.setdefault(blob["digest"], blob)
        fetched = dict(zip(unique.keys(),
                           parallel_fetch(list(unique.values()), fetch_blob)))
        report.files.extend(fetched[blob["digest"]] for blob in blobs)
        report.secs = time.perf_counter() - t0
        log.info("pulled %s:%s — %d blobs, %d bytes", name, tag,
                 len(report.files), report.total_bytes)
        return report
