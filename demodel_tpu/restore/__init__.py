from demodel_tpu.restore.client import restore
from demodel_tpu.restore.server import RestoreRegistry, RestoreServer

__all__ = ["restore", "RestoreRegistry", "RestoreServer"]
