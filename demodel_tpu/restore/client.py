"""Restore client: ``/restore`` endpoint → sharded device arrays.

The consumer half of the north-star restore path: a serving stack
(JetStream/MaxText-style) points at a demodel-tpu node instead of GCS and
restores a checkpoint straight into HBM under its own shardings. Each
device's shard is fetched as an HTTP **Range** of the tensor's bytes — on a
multi-host mesh every host pulls only its addressable slice, so restore
bandwidth scales with hosts.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import requests
from jax.sharding import Mesh

from demodel_tpu.formats.safetensors import _np_dtype
from demodel_tpu.parallel.mesh import make_mesh
from demodel_tpu.utils import trace
from demodel_tpu.utils.env import env_int
from demodel_tpu.sink.hbm import Placement, place_tensor
from demodel_tpu.sink.plan import ShardingPlan
from demodel_tpu.utils.faults import (
    PeerHealth,
    RetryPolicy,
    request_with_retry,
)
from demodel_tpu.utils.logging import get_logger

log = get_logger("restore.client")


@dataclass
class RestoreResult(Placement):
    secs: float = 0.0
    bytes_fetched: int = 0
    manifest: dict = field(default_factory=dict)


def restore(
    endpoint: str,
    model: str,
    mesh: Mesh | None = None,
    plan: ShardingPlan | None = None,
    cast_to=None,
    session: requests.Session | None = None,
    timeout: float = 300.0,
) -> RestoreResult:
    """Restore ``model`` from a demodel-tpu ``/restore`` endpoint."""
    with trace.span("restore", model=model, endpoint=endpoint):
        return _restore(endpoint, model, mesh, plan, cast_to, session,
                        timeout)


def _restore(endpoint, model, mesh, plan, cast_to, session,
             timeout) -> RestoreResult:
    if mesh is None:
        mesh = make_mesh()
    if plan is None:
        plan = ShardingPlan(mesh)
    s = session or requests.Session()
    endpoint = endpoint.rstrip("/")
    t0 = time.perf_counter()

    # manifest + tensor windows ride the shared wire-robustness layer:
    # retries with backoff here, window-level resume/failover inside
    # PeerBlobReader below
    r = request_with_retry(
        s, "GET", f"{endpoint}/restore/{model}/manifest",
        policy=RetryPolicy(), health=PeerHealth.shared(), peer=endpoint,
        timeout=timeout, what=f"restore manifest {model}")
    manifest = r.json()

    out = RestoreResult(mesh_desc=f"{dict(mesh.shape)}", manifest=manifest)
    fetched = 0
    fetched_lock = threading.Lock()
    # bytes ride the native data plane when the node advertises one
    data_base = manifest.get("data_endpoint", endpoint).rstrip("/")

    def restore_one(item):
        name, info = item
        with trace.span("tensor-restore", tensor=name,
                        bytes=int(info["nbytes"])):
            return _restore_one(name, info)

    def _restore_one(name, info):
        shape = tuple(info["shape"])
        np_dtype = _np_dtype(info["dtype"])
        sharding = plan.sharding_for(name, shape, np_dtype.itemsize)
        # large shard windows ride the native multi-stream fan-out
        # (straight into the device_put buffer); small ones a ranged GET
        from demodel_tpu.sink.remote import PeerBlobReader

        reader = PeerBlobReader(
            data_base, name, int(info["nbytes"]),
            path=f"/restore/{model}/tensor/{name}", timeout=timeout)

        def done() -> None:
            nonlocal fetched
            with fetched_lock:
                fetched += reader.bytes_fetched

        read_at = lambda off, ln: reader.pread(name, ln, off)  # noqa: E731
        read_into = lambda off, out: reader.pread_into(name, out, off)  # noqa: E731
        arr = place_tensor(read_at, shape, np_dtype, 0, sharding, cast_to,
                           read_into=read_into)
        done()
        return name, arr

    # tensor-level fan-out: a restore is many independent range reads; a
    # small pool hides HTTP latency (device_put is thread-safe)
    items = list(manifest["tensors"].items())
    workers = min(env_int("DEMODEL_RESTORE_WORKERS", 8, minimum=1),
                  max(1, len(items)))
    if workers > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as ex:
            # trace.wrap PER ITEM: pool threads don't inherit contextvars
            # (this keeps per-tensor spans under the restore root), and a
            # contextvars.Context is single-entrant — one shared wrapped
            # fn across concurrent workers would raise "cannot enter
            # context"
            futs = [ex.submit(trace.wrap(restore_one), item)
                    for item in items]
            for fut in futs:
                name, arr = fut.result()
                out.arrays[name] = arr
    else:
        for item in items:
            name, arr = restore_one(item)
            out.arrays[name] = arr
    out.secs = time.perf_counter() - t0
    out.bytes_fetched = fetched
    log.info("restored %s: %d tensors, %.1f MB fetched in %.2fs",
             model, len(out.arrays), fetched / 1e6, out.secs)
    return out
