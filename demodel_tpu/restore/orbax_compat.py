"""Orbax interop: delivered Placements ↔ Orbax checkpoints.

Closes the loop with the wider JAX ecosystem: a model pulled through the
proxy and landed in HBM can be written as a standard Orbax checkpoint (for
tools that insist on GCS/disk checkpoints), and an existing Orbax checkpoint
can be loaded back under delivery shardings. This — not a reimplementation
of TensorStore — is the pragmatic "Orbax-compatible" surface: the HTTP
restore path (:mod:`demodel_tpu.restore`) for demodel-tpu-aware consumers,
and real Orbax files for everyone else.
"""

from __future__ import annotations

from pathlib import Path

import jax

from demodel_tpu.sink.hbm import Placement
from demodel_tpu.utils.logging import get_logger

log = get_logger("restore.orbax")


def _nest(flat: dict[str, jax.Array]) -> dict:
    """'a.b.c' keys → nested dict (Orbax trees are nested)."""
    tree: dict = {}
    for name, arr in flat.items():
        node = tree
        parts = name.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def _flatten(tree: dict, prefix: str = "") -> dict:
    flat = {}
    for k, v in tree.items():
        name = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            flat.update(_flatten(v, name))
        else:
            flat[name] = v
    return flat


def save_placement(placement: Placement, path: Path | str) -> None:
    """Write a delivered Placement as a standard Orbax checkpoint."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, _nest(placement.arrays))
    log.info("saved %d tensors to orbax checkpoint %s", len(placement.arrays), path)


def load_placement(path: Path | str, shardings: dict | None = None) -> Placement:
    """Load an Orbax checkpoint back into a Placement (optionally resharded
    with ``shardings``: flat name → NamedSharding)."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    with ocp.StandardCheckpointer() as ckptr:
        if shardings:
            meta = ckptr.metadata(path)
            flat_meta = _flatten(meta)
            restore_tree = {}
            for name, m in flat_meta.items():
                sh = shardings.get(name)
                restore_tree[name] = ocp.utils.to_shape_dtype_struct(m, sharding=sh) \
                    if sh is not None else m
            tree = ckptr.restore(path, _nest(restore_tree))
        else:
            tree = ckptr.restore(path)
    flat = _flatten(tree)
    out = Placement(arrays=flat, mesh_desc="orbax")
    log.info("loaded %d tensors from orbax checkpoint %s", len(flat), path)
    return out
