"""Network-Orbax restore: an ``orbax.checkpoint`` handler over ``/restore``.

The north star's defining sentence (``BASELINE.json``; successor of the
legacy axum API server, ``/root/reference/Cargo.lock:458-474``): a consumer
that speaks only Orbax — JetStream/MaxText-style serving stacks — points its
checkpointer at a demodel-tpu node *instead of GCS* and restores a pulled
model straight into sharded device arrays. No local checkpoint files exist
at any point: every tensor shard arrives as an HTTP Range read of the
``/restore/{model}/tensor/{name}`` endpoint.

Usage (the consumer side, pure Orbax API)::

    import orbax.checkpoint as ocp
    from demodel_tpu.restore.orbax_http import (
        HTTPRestoreArgs, HTTPRestoreCheckpointHandler,
    )

    ckptr = ocp.Checkpointer(
        HTTPRestoreCheckpointHandler(endpoint="http://node:8081"))
    tree = ckptr.restore(".", args=HTTPRestoreArgs(
        model="meta-llama/Llama-2-7b", item=abstract_train_state))

``item`` is the usual abstract target pytree (``jax.ShapeDtypeStruct``
leaves carrying ``NamedSharding``); each leaf restores under exactly the
requested sharding, each host fetching only its addressable byte ranges.
``ckptr.restore``'s *path* argument is vestigial (Orbax insists on an
existing directory — pass ``"."``); the checkpoint identity is
``args.model`` on the wire.

``save`` is implemented too: the pytree is serialized to safetensors and
``PUT`` to the node, which commits it to the content-addressed store and
registers it for restore — a trained model becomes peer-distributable
through the same delivery plane.
"""

from __future__ import annotations

import dataclasses
import hashlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import jax
import numpy as np

from demodel_tpu.formats.safetensors import _np_dtype
from demodel_tpu.sink.hbm import place_tensor
from demodel_tpu.sink.plan import ShardingPlan
from demodel_tpu.utils.env import env_int
from demodel_tpu.utils.logging import get_logger

import orbax.checkpoint as ocp

log = get_logger("restore.orbax_http")


def _flatten_tree(tree) -> dict[str, Any]:
    """Pytree → {'a.b.c': leaf} using the same '.'-joined names the
    safetensors manifests use (dict keys / sequence indices / field names)."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        parts = []
        for p in path:
            if isinstance(p, jax.tree_util.DictKey):
                parts.append(str(p.key))
            elif isinstance(p, jax.tree_util.SequenceKey):
                parts.append(str(p.idx))
            elif isinstance(p, jax.tree_util.GetAttrKey):
                parts.append(str(p.name))
            else:
                parts.append(str(p))
        flat[".".join(parts)] = leaf
    return flat


def _nest(flat: dict[str, Any]) -> dict:
    """'a.b.c' keys → nested dict (the inverse of :func:`_flatten_tree`
    for dict-shaped trees)."""
    tree: dict = {}
    for name, arr in flat.items():
        node = tree
        parts = name.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


@dataclasses.dataclass
class HTTPRestoreArgs(ocp.args.CheckpointArgs):
    """Restore args: which model to pull off the wire and (optionally) the
    abstract target tree whose shardings/dtypes govern placement."""

    model: str
    #: abstract pytree (ShapeDtypeStruct leaves, optionally with sharding);
    #: None restores every tensor in the manifest under ``plan``
    item: Any = None
    mesh: Any = None
    plan: Any = None
    cast_to: Any = None


@dataclasses.dataclass
class HTTPSaveArgs(ocp.args.CheckpointArgs):
    """Save args: pytree to serialize and push to the node."""

    item: Any
    model: str


class HTTPRestoreCheckpointHandler(ocp.CheckpointHandler):
    """``ocp.CheckpointHandler`` whose storage backend is a demodel-tpu
    ``/restore`` HTTP endpoint instead of a filesystem/GCS directory."""

    def __init__(self, endpoint: str, timeout: float = 300.0,
                 workers: int | None = None):
        import threading

        import requests

        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout
        self.workers = workers or env_int("DEMODEL_RESTORE_WORKERS", 8,
                                          minimum=1)
        self._tls = threading.local()
        self._requests = requests

    @property
    def _session(self):
        s = getattr(self._tls, "session", None)
        if s is None:
            s = self._tls.session = self._requests.Session()
        return s

    # -- manifest / metadata -------------------------------------------
    def _manifest(self, model: str) -> dict:
        r = self._session.get(f"{self.endpoint}/restore/{model}/manifest",
                              timeout=self.timeout)
        r.raise_for_status()
        return r.json()

    def metadata(self, directory=None, model: str | None = None):
        """Abstract tree of the checkpoint (ShapeDtypeStructs). ``model``
        is required when called directly; via Orbax pass it in args."""
        if model is None:
            raise ValueError("metadata() needs model= (the HTTP checkpoint "
                             "identity lives on the wire, not in directory)")
        manifest = self._manifest(model)
        flat = {
            name: jax.ShapeDtypeStruct(tuple(info["shape"]),
                                       _np_dtype(info["dtype"]))
            for name, info in manifest["tensors"].items()
        }
        return _nest(flat)

    # -- restore --------------------------------------------------------
    def _restore_one(self, model: str, name: str, info: dict, sharding,
                     cast_to, data_base: str | None = None) -> jax.Array:
        shape = tuple(info["shape"])
        np_dtype = _np_dtype(info["dtype"])
        base = (data_base or self.endpoint).rstrip("/")
        # the window reader fans large shard reads out over native range
        # streams (socket bytes land in the device_put buffer) and falls
        # back to single ranged GETs for small windows / https endpoints
        from demodel_tpu.sink.remote import PeerBlobReader

        reader = PeerBlobReader(
            base, name, int(info["nbytes"]),
            path=f"/restore/{model}/tensor/{name}", timeout=self.timeout)
        read_at = lambda off, ln: reader.pread(name, ln, off)  # noqa: E731
        read_into = lambda off, out: reader.pread_into(name, out, off)  # noqa: E731
        return place_tensor(read_at, shape, np_dtype, 0, sharding, cast_to,
                            read_into=read_into)

    def restore(self, directory=None, args: HTTPRestoreArgs | None = None):
        if args is None:
            raise ValueError("pass args=HTTPRestoreArgs(model=..., item=...)")
        manifest = self._manifest(args.model)
        tensors = manifest["tensors"]

        from demodel_tpu.parallel.mesh import make_mesh

        mesh = args.mesh if args.mesh is not None else make_mesh()
        plan = args.plan if args.plan is not None else ShardingPlan(mesh)

        if args.item is not None:
            targets = _flatten_tree(args.item)
            missing = sorted(set(targets) - set(tensors))
            if missing:
                raise KeyError(
                    f"{args.model}: tensors not in checkpoint: {missing[:5]}")
            jobs = []
            for name, leaf in targets.items():
                info = tensors[name]
                sharding = getattr(leaf, "sharding", None)
                if sharding is None:
                    sharding = plan.sharding_for(
                        name, tuple(info["shape"]),
                        _np_dtype(info["dtype"]).itemsize)
                want_dtype = getattr(leaf, "dtype", None)
                cast = None
                if want_dtype is not None and \
                        np.dtype(want_dtype) != _np_dtype(info["dtype"]):
                    cast = want_dtype
                if tuple(getattr(leaf, "shape", tuple(info["shape"]))) != \
                        tuple(info["shape"]):
                    raise ValueError(
                        f"{name}: target shape {leaf.shape} != checkpoint "
                        f"shape {tuple(info['shape'])}")
                jobs.append((name, info, sharding, cast or args.cast_to))
        else:
            jobs = [
                (name, info,
                 plan.sharding_for(name, tuple(info["shape"]),
                                   _np_dtype(info["dtype"]).itemsize),
                 args.cast_to)
                for name, info in tensors.items()
            ]

        flat: dict[str, jax.Array] = {}
        data_base = manifest.get("data_endpoint")
        if data_base:
            data_base = data_base.rstrip("/")
        # tensor-level fan-out: restores are many independent range reads,
        # so a small pool hides HTTP latency; device_put is thread-safe
        with ThreadPoolExecutor(max_workers=min(self.workers, max(1, len(jobs)))) as ex:
            futs = {
                ex.submit(self._restore_one, args.model, name, info,
                          sharding, cast, data_base): name
                for name, info, sharding, cast in jobs
            }
            for fut, name in futs.items():
                flat[name] = fut.result()
        log.info("orbax-http restored %s: %d tensors from %s",
                 args.model, len(flat), self.endpoint)
        if args.item is not None:
            # rebuild the caller's tree structure with restored leaves
            leaves_by_name = flat
            paths = jax.tree_util.tree_flatten_with_path(args.item)
            names = list(_flatten_tree(args.item).keys())
            restored_leaves = [leaves_by_name[n] for n in names]
            return jax.tree_util.tree_unflatten(paths[1], restored_leaves)
        return _nest(flat)

    # -- save -----------------------------------------------------------
    def save(self, directory=None, args: HTTPSaveArgs | None = None):
        """Streamed per-tensor push (VERDICT r3 #7): each tensor is
        materialized on the host ONE AT A TIME, digested, skipped when the
        node already holds its bytes (content-address dedup — an unchanged
        tensor in a checkpoint loop is never re-transferred), and PUT as a
        single-tensor safetensors blob otherwise. Peak client RAM is
        O(largest tensor), not O(checkpoint); the server streams too. A
        final commit registers the model from the ordered digest list."""
        if args is None:
            raise ValueError("pass args=HTTPSaveArgs(item=..., model=...)")
        from demodel_tpu.formats import safetensors as st

        flat = _flatten_tree(args.item)
        digests: list[str] = []
        pushed = skipped = 0
        sent_bytes = 0
        for name, a in flat.items():
            # one tensor at a time: host copy + its blob are the only
            # per-iteration allocations, freed before the next tensor
            blob = st.serialize({name: np.asarray(a)})
            digest = hashlib.sha256(blob).hexdigest()
            digests.append(digest)
            probe = self._session.get(
                f"{self.endpoint}/restore/blob/{digest}",
                timeout=self.timeout)
            if probe.status_code == 200:
                skipped += 1
                continue
            r = self._session.put(
                f"{self.endpoint}/restore/blob/{digest}", data=blob,
                timeout=self.timeout,
                headers={"Content-Type": "application/octet-stream"})
            r.raise_for_status()
            pushed += 1
            sent_bytes += len(blob)
        r = self._session.post(
            f"{self.endpoint}/restore/{args.model}/commit",
            json={"digests": digests}, timeout=self.timeout)
        r.raise_for_status()
        log.info("orbax-http saved %s: %d tensors (%d pushed, %.1f MB sent; "
                 "%d deduped) to %s", args.model, len(digests), pushed,
                 sent_bytes / 1e6, skipped, self.endpoint)
        # ocp.Checkpointer discards this; direct callers (save_pytree) get
        # the dedup accounting for tests/telemetry
        return {"tensors": len(digests), "pushed": pushed,
                "skipped": skipped, "sent_bytes": sent_bytes}

    @classmethod
    def typestr(cls) -> str:
        return "demodel_tpu.HTTPRestoreCheckpointHandler"

    def finalize(self, directory=None) -> None:
        pass

    def close(self) -> None:
        pass


# register with Orbax's args machinery so ocp.Checkpointer(handler) can
# construct_checkpoint_args for save/restore calls
ocp.args.register_with_handler(
    HTTPRestoreCheckpointHandler, for_restore=True)(HTTPRestoreArgs)
ocp.args.register_with_handler(
    HTTPRestoreCheckpointHandler, for_save=True)(HTTPSaveArgs)


# plain-function conveniences for non-Orbax callers ----------------------


def restore_pytree(endpoint: str, model: str, item=None, mesh=None,
                   plan=None, cast_to=None):
    """One-call network restore (no ocp.Checkpointer ceremony)."""
    h = HTTPRestoreCheckpointHandler(endpoint)
    return h.restore(args=HTTPRestoreArgs(model=model, item=item, mesh=mesh,
                                          plan=plan, cast_to=cast_to))


def save_pytree(endpoint: str, model: str, item) -> dict:
    """Push a pytree to a node's restore surface (streamed, per-tensor,
    content-deduped). Returns {tensors, pushed, skipped, sent_bytes}."""
    h = HTTPRestoreCheckpointHandler(endpoint)
    return h.save(args=HTTPSaveArgs(item=item, model=model))
