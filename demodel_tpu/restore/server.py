"""Restore API server — the successor of the legacy Rust generation's axum
control/serving surface (``Cargo.lock:458-474``, SURVEY.md §2.2) and the
north star's "Orbax-compatible ``/restore`` endpoint that JetStream/MaxText
hit instead of GCS" (``BASELINE.json``).

Serves checkpoint-shaped HTTP over the content-addressed store:

- ``GET /restore/models``                    → registered model names
- ``GET /restore/{model}/manifest``          → pytree skeleton: every tensor's
  dtype/shape/nbytes (+ which stored blob holds it)
- ``GET /restore/{model}/tensor/{name}``     → that tensor's raw bytes,
  **Range-aware** so a restoring host fetches exactly its shards' byte
  ranges — the property that makes sharded multi-host restore bandwidth-
  optimal (each byte crosses DCN once).

Tensor-name addressing (rather than file addressing) is what Orbax-style
restores need; actual Orbax checkpoint interop lives in
:mod:`demodel_tpu.restore.orbax_compat`.
"""

from __future__ import annotations

import json
import queue
import re
import threading
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from demodel_tpu.formats import safetensors as st
from demodel_tpu.store import Store
from demodel_tpu.utils import metrics, trace
from demodel_tpu.utils.logging import get_logger
from demodel_tpu.utils.metrics import labeled

log = get_logger("restore")

#: pre-register the /generate HTTP outcome families (house idiom) — the
#: serve plane itself may never be imported on this node, but the scrape
#: should still type the surface
for _code in ("200", "400", "411", "413", "500", "503", "504"):
    metrics.HUB.inc(labeled("gen_http_total", code=_code), 0)


def _gen_engine():
    """Resolve the process-wide generation engine WITHOUT importing the
    serve plane (which imports jax): an engine can only exist if this
    process booted one (``serve.boot``/``serve.load_model``) — a
    dep-light restore node that never serves tokens answers 503 and
    never pays the import. Returns (serve module, engine) or (None,
    None)."""
    import sys

    serve = sys.modules.get("demodel_tpu.serve")
    if serve is None:
        return None, None
    return serve, serve.current()


def _swarm_board(pull_id: str, host_id: str):
    """Resolve a swarm chunk board WITHOUT importing the swarm plane: a
    board can only exist if this process runs a :class:`SwarmScheduler`
    (which imports the placement module) — a dep-light restore node that
    never swarms answers 404 and never pays the import."""
    import sys

    placement = sys.modules.get("demodel_tpu.parallel.placement")
    if placement is None:
        return None
    return placement.board(pull_id, host_id)


@dataclass(frozen=True)
class _TensorLoc:
    key: str      # store key of the safetensors blob
    dtype: str    # safetensors dtype tag
    shape: tuple[int, ...]
    start: int    # absolute offset within the blob
    nbytes: int


class RestoreRegistry:
    """model name → tensor locations, built from stored safetensors blobs."""

    def __init__(self, store: Store):
        self.store = store
        self._models: dict[str, dict[str, _TensorLoc]] = {}
        self._pinned: dict[str, list[str]] = {}  # model → GC-pinned keys
        self._lock = threading.Lock()
        self._native = None  # ProxyServer carrying the C++ data plane
        self._native_port: int | None = None
        self._data_endpoint: str | None = None

    def register_safetensors(self, model: str, keys: list[str]) -> int:
        if not keys:
            raise ValueError(f"model {model}: no safetensors blobs to register")
        tensors: dict[str, _TensorLoc] = {}
        for key in keys:
            index = st.read_index_from(
                lambda off, ln, k=key: self.store.pread(k, ln, off)
            )
            for name, spec in index.tensors.items():
                if name in tensors:
                    raise ValueError(f"duplicate tensor {name} in model {model}")
                tensors[name] = _TensorLoc(
                    key=key, dtype=spec.dtype, shape=spec.shape,
                    start=spec.start, nbytes=spec.nbytes,
                )
        for key in keys:
            # GC must not evict a blob this registry is advertising
            # (ADVICE r3 medium); the native proxy pins its own store
            # instance when the mapping is mirrored below. Pins are
            # refcounted, and a re-registration releases the replaced
            # checkpoint's pins — otherwise every model update would leak
            # a full checkpoint out of the GC cap's reach.
            self.store.pin(key)
        with self._lock:
            old_keys = self._pinned.pop(model, [])
            stale = set(self._models.get(model, ())) - set(tensors)
            self._pinned[model] = list(keys)
            self._models[model] = tensors
            native = self._native
        if native is not None:
            # mirror the mapping into the C++ data plane: tensor bytes then
            # serve from the proxy port via sendfile, GIL-free. New-set
            # entries first (same-name tensors replace atomically under
            # the native lock, pin-new-before-unpin-old), THEN drop only
            # the names absent from the new set — a drop-all-re-add
            # window would briefly 404 live fetches of kept tensors and
            # leave their keys unpinned against a concurrent GC
            # (advisor r4 + reviewer r5)
            for name, loc in tensors.items():
                native.register_tensor(model, name, loc.key, loc.start,
                                       loc.nbytes)
            for name in stale:
                native.unregister_tensor(model, name)
        # Python-handle pins released only after the native mirror holds
        # its own pins on every new-set key: no instant at which a kept
        # blob is pin-free
        for key in old_keys:
            self.store.unpin(key)
        log.info("registered model %s: %d tensors", model, len(tensors))
        return len(tensors)

    def register_report(self, model: str, report) -> int:
        files = report.files if hasattr(report, "files") else report["files"]
        keys = [
            (f.key if hasattr(f, "key") else f["key"])
            for f in files
            if (f.name if hasattr(f, "name") else f["name"]).endswith(".safetensors")
        ]
        return self.register_safetensors(model, keys)

    def attach_native(self, proxy, advertise: str | None = None) -> None:
        """Serve tensor bytes from ``proxy``'s C++ plane (VERDICT r2 weak
        #5: the GIL-bound Python server capped the north-star restore
        path). Existing and future registrations are mirrored; manifests
        advertise the data endpoint so clients fetch bytes there.

        ``advertise`` (or ``DEMODEL_ADVERTISE_HOST``) pins the host name
        remote clients should use. Without it, the endpoint host is derived
        per-request from the manifest request's ``Host`` header (ADVICE r3
        high: advertising ``proxy.url`` handed remote restore clients a
        ``127.0.0.1`` URL — their OWN machine — whenever the proxy bound
        0.0.0.0)."""
        import os

        advertise = advertise or os.environ.get("DEMODEL_ADVERTISE_HOST")
        with self._lock:
            self._native = proxy
            self._native_port = proxy.port
            if advertise:
                if advertise.startswith("["):
                    # bracketed IPv6, maybe with port
                    host = advertise if "]:" in advertise else \
                        f"{advertise}:{proxy.port}"
                elif advertise.count(":") > 1:
                    # bare IPv6 literal: bracket it, then add the port
                    host = f"[{advertise}]:{proxy.port}"
                elif ":" in advertise:
                    host = advertise  # host:port already
                else:
                    host = f"{advertise}:{proxy.port}"
                self._data_endpoint = f"http://{host}"
            elif proxy.cfg.host not in ("0.0.0.0", ""):
                # explicit bind address: externally meaningful, advertise it
                self._data_endpoint = proxy.url
            else:
                # wildcard bind: no single routable name exists — leave the
                # static endpoint unset and derive per-request (manifest())
                self._data_endpoint = None
            models = {m: dict(t) for m, t in self._models.items()}
        for model, tensors in models.items():
            for name, loc in tensors.items():
                proxy.register_tensor(model, name, loc.key, loc.start,
                                      loc.nbytes)

    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def unregister(self, model: str) -> bool:
        """Full teardown of a model: drop it from the registry AND the
        native data plane, releasing every pin so GC can reclaim the
        checkpoint. Returns False when the model wasn't registered."""
        with self._lock:
            if model not in self._models:
                return False
            del self._models[model]
            old_keys = self._pinned.pop(model, [])
            native = self._native
        if native is not None:
            native.unregister_model(model)
        for key in old_keys:
            self.store.unpin(key)
        log.info("unregistered model %s", model)
        return True

    def put_safetensors(self, model: str, src, length: int) -> int:
        """Commit a pushed safetensors blob (``src``: readable stream of
        ``length`` bytes) into the store and register it for restore — the
        server half of the network-Orbax *save* path. Returns the tensor
        count. A re-push replaces the previous registration."""
        from demodel_tpu.store import key_for_uri

        key = key_for_uri(f"demodel://restore/{model}/pushed")
        if self.store.has(key):
            self.store.remove(key)
        w = self.store.begin(key)
        try:
            remaining = length
            while remaining > 0:
                chunk = src.read(min(1 << 20, remaining))
                if not chunk:
                    raise ValueError(f"body truncated at {length - remaining}"
                                     f"/{length} bytes")
                w.append(chunk)
                remaining -= len(chunk)
            w.commit({"kind": "pushed-checkpoint", "model": model,
                      "size": length})
        except BaseException:
            if w._open:  # noqa: SLF001 — writer state check
                w.abort(keep_partial=False)
            raise
        try:
            return self.register_safetensors(model, [key])
        except Exception:
            # an unparsable blob must not stay registered or cached
            self.store.remove(key)
            raise

    # -- streamed per-tensor push (VERDICT r3 #7) ----------------------

    @staticmethod
    def _tensor_blob_key(digest: str) -> str:
        from demodel_tpu.store import key_for_uri

        return key_for_uri(f"demodel://restore/tensor/{digest}")

    def has_tensor_blob(self, digest: str) -> bool:
        """True when a pushed single-tensor blob with this content digest
        is already stored — the dedup probe of the streamed save: an
        unchanged tensor is never re-transferred or re-stored."""
        return self.store.has(self._tensor_blob_key(digest))

    def put_tensor_blob(self, digest: str, src, length: int) -> None:
        """Commit one single-tensor safetensors blob under its content
        address. Streamed in 1 MB chunks (server RAM is O(1)); the store's
        rolling sha256 must match ``digest`` or the push is rejected."""
        if not (len(digest) == 64
                and all(c in "0123456789abcdef" for c in digest)):
            raise ValueError("digest must be 64 hex chars")
        key = self._tensor_blob_key(digest)
        if self.store.has(key):
            # content-addressed: same digest == same bytes; drain the body
            # so the connection stays usable, then no-op
            remaining = length
            while remaining > 0:
                chunk = src.read(min(1 << 20, remaining))
                if not chunk:
                    break
                remaining -= len(chunk)
            return
        w = self.store.begin(key)
        try:
            remaining = length
            while remaining > 0:
                chunk = src.read(min(1 << 20, remaining))
                if not chunk:
                    raise ValueError(f"body truncated at {length - remaining}"
                                     f"/{length} bytes")
                w.append(chunk)
                remaining -= len(chunk)
            got = w.digest()
            if got != digest:
                w.abort(keep_partial=False)
                raise ValueError(f"blob digest mismatch: got {got}")
            w.commit({"kind": "pushed-tensor", "sha256": digest,
                      "size": length})
        except BaseException:
            if w._open:  # noqa: SLF001 — writer state check
                w.abort(keep_partial=False)
            raise

    def commit_push(self, model: str, digests: list[str]) -> int:
        """Register ``model`` from previously pushed per-tensor blobs.
        Returns the tensor count; unknown digests raise before any
        registration changes."""
        keys = []
        for d in digests:
            key = self._tensor_blob_key(d)
            if not self.store.has(key):
                raise ValueError(f"no pushed tensor blob for digest {d[:12]}")
            keys.append(key)
        return self.register_safetensors(model, keys)

    def _lazy_resolve(self, model: str) -> bool:
        """Register ``model`` from a pull-manifest record in the store
        (written by :func:`demodel_tpu.delivery.pull`), if one exists."""
        import json as _json

        from demodel_tpu.delivery import manifest_key

        for source in ("hf", "ollama"):
            mkey = manifest_key(source, model)
            if not self.store.has(mkey):
                continue
            try:
                record = _json.loads(self.store.get(mkey).decode())
                self.register_report(model, record)
                return True
            except (ValueError, KeyError) as e:
                log.warning("manifest record for %s unusable: %s", model, e)
        return False

    def manifest(self, model: str, request_host: str | None = None) -> dict | None:
        """``request_host``: the manifest request's ``Host`` header. When the
        native plane is attached on a wildcard bind, the data endpoint is
        the host the CLIENT reached us by, with the native port swapped in —
        the only name known to be routable from that client."""
        with self._lock:
            tensors = self._models.get(model)
        if tensors is None and self._lazy_resolve(model):
            with self._lock:
                tensors = self._models.get(model)
        if tensors is None:
            return None
        out = {
            "model": model,
            "format": "safetensors-ranges",
            "tensors": {
                name: {"dtype": t.dtype, "shape": list(t.shape), "nbytes": t.nbytes}
                for name, t in tensors.items()
            },
        }
        # bytes live on the native plane; this server stays control-only
        if self._data_endpoint:
            out["data_endpoint"] = self._data_endpoint
        elif self._native_port is not None and request_host:
            host = request_host.rsplit(":", 1)[0] if not request_host.startswith("[") \
                else request_host.rpartition("]")[0] + "]"
            out["data_endpoint"] = f"http://{host}:{self._native_port}"
        return out

    def locate(self, model: str, tensor: str) -> _TensorLoc | None:
        with self._lock:
            loc = self._models.get(model, {}).get(tensor)
        if loc is None and model not in self.models() and self._lazy_resolve(model):
            with self._lock:
                loc = self._models.get(model, {}).get(tensor)
        return loc


def make_handler(registry: RestoreRegistry, proxy=None):
    class RestoreHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _send(self, status, body: bytes, ctype="application/json", extra=None):
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            if self.command != "HEAD":
                self.wfile.write(body)

        def do_HEAD(self):
            self.do_GET()

        def _traced(self, fn):
            """Run one request handler under a server-side span, parented
            on the client's W3C ``traceparent`` header when present — the
            server half of the cross-host trace stitch. No-op (a shared
            noop span, zero allocation) when tracing is disabled."""
            with trace.span("serve.restore",
                            remote_parent=self.headers.get("traceparent"),
                            method=self.command, path=self.path):
                return fn()

        def _content_length(self) -> int:
            try:
                return int(self.headers.get("Content-Length", "0"))
            except ValueError:
                return 0

        def do_PUT(self):
            self._traced(self._put)

        def _put(self):
            # push surfaces for the network-Orbax save path:
            #   /restore/{model}/safetensors — one whole-checkpoint blob
            #   /restore/blob/{digest}       — one single-tensor blob,
            #     content-addressed (streamed save; VERDICT r3 #7)
            m = re.match(r"^/restore/blob/([0-9a-f]{64})$", self.path)
            if m:
                length = self._content_length()
                if length <= 0:
                    self._send(411, b'{"error":"Content-Length required"}')
                    return
                try:
                    registry.put_tensor_blob(m.group(1), self.rfile, length)
                except Exception as e:  # noqa: BLE001 — bad blob → client error
                    self._send(400, json.dumps({"error": str(e)}).encode())
                    return
                metrics.HUB.inc("restore_put_bytes_total", length)
                self._send(200, b'{"ok":true}')
                return
            m = re.match(r"^/restore/(.+)/safetensors$", self.path)
            if m is None:
                self._send(404, b'{"error":"not found"}')
                return
            model = m.group(1)
            length = self._content_length()
            if length <= 0:
                self._send(411, b'{"error":"Content-Length required"}')
                return
            try:
                n = registry.put_safetensors(model, self.rfile, length)
            except Exception as e:  # noqa: BLE001 — bad blob → client error
                self._send(400, json.dumps({"error": str(e)}).encode())
                return
            metrics.HUB.inc("restore_put_total")
            metrics.HUB.inc("restore_put_bytes_total", length)
            self._send(200, json.dumps({"model": model, "tensors": n}).encode())

        def do_POST(self):
            self._traced(self._post)

        def _post(self):
            if self.path == "/generate":
                self._generate()
                return
            # finalize a streamed save: the ordered digest list becomes the
            # model registration (every blob must already be pushed)
            m = re.match(r"^/restore/(.+)/commit$", self.path)
            if m is None:
                self._send(404, b'{"error":"not found"}')
                return
            length = self._content_length()
            if not 0 < length <= (16 << 20):
                self._send(411, b'{"error":"Content-Length required"}')
                return
            try:
                body = json.loads(self.rfile.read(length))
                digests = body["digests"]
                if not isinstance(digests, list) or not digests:
                    raise ValueError("digests must be a non-empty list")
                n = registry.commit_push(m.group(1), digests)
            except Exception as e:  # noqa: BLE001 — bad commit → client error
                self._send(400, json.dumps({"error": str(e)}).encode())
                return
            metrics.HUB.inc("restore_put_total")
            self._send(200, json.dumps({"model": m.group(1),
                                        "tensors": n}).encode())

        def _generate(self):  # noqa: C901
            # the token-serving surface: tokens-in, tokens-out against
            # the process-wide continuous-batching engine. Dep-light:
            # no engine booted → 503, the jax import never happens here.
            serve, engine = _gen_engine()
            if engine is None:
                metrics.HUB.inc(labeled("gen_http_total", code="503"))
                self._send(503, b'{"error":"serving disabled '
                                b'(no engine booted)"}')
                return
            length = self._content_length()
            if length <= 0:
                metrics.HUB.inc(labeled("gen_http_total", code="411"))
                self._send(411, b'{"error":"Content-Length required"}')
                return
            if length > (8 << 20):
                metrics.HUB.inc(labeled("gen_http_total", code="413"))
                self._send(413, b'{"error":"body exceeds 8 MiB limit"}')
                return
            try:
                body = json.loads(self.rfile.read(length))
                prompt = body["prompt"]
                if not isinstance(prompt, list) or not prompt:
                    raise ValueError(
                        "prompt must be a non-empty list of token ids")
                max_new = int(body.get("max_new_tokens", 16))
                stream = bool(body.get("stream", False))
                timeout = float(body.get("timeout", 300.0))
            except Exception as e:  # noqa: BLE001 — bad body → client error
                metrics.HUB.inc(labeled("gen_http_total", code="400"))
                self._send(400, json.dumps({"error": str(e)}).encode())
                return
            try:
                req = engine.submit(prompt, max_new)
            except serve.QueueOverflow as e:
                # the proxy plane's admission contract: loud rejection
                # with a backoff hint, never a silent drop
                metrics.HUB.inc(labeled("gen_http_total", code="503"))
                self._send(503, json.dumps({
                    "error": str(e),
                    "retry_after": e.retry_after}).encode(),
                    extra={"Retry-After": str(e.retry_after)})
                return
            except (ValueError, RuntimeError) as e:
                metrics.HUB.inc(labeled("gen_http_total", code="400"))
                self._send(400, json.dumps({"error": str(e)}).encode())
                return
            if not stream:
                try:
                    toks = req.result(timeout=timeout)
                except TimeoutError:
                    req.cancel()
                    metrics.HUB.inc(labeled("gen_http_total", code="504"))
                    self._send(504, b'{"error":"generation timed out"}')
                    return
                except RuntimeError as e:
                    metrics.HUB.inc(labeled("gen_http_total", code="500"))
                    self._send(500,
                               json.dumps({"error": str(e)}).encode())
                    return
                metrics.HUB.inc(labeled("gen_http_total", code="200"))
                self._send(200, json.dumps({
                    "id": req.id, "tokens": toks,
                    "prompt_tokens": len(req.prompt),
                    "queue_ms": round(
                        ((req.started_s or req.submitted_s)
                         - req.submitted_s) * 1e3, 3),
                    "total_ms": round(
                        ((req.finished_s or req.submitted_s)
                         - req.submitted_s) * 1e3, 3)}).encode())
                return
            # streaming: chunked NDJSON — one {"token": id} line as each
            # token decodes, then a {"done": true} summary line
            metrics.HUB.inc(labeled("gen_http_total", code="200"))
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def _chunk(obj) -> None:
                data = (json.dumps(obj) + "\n").encode()
                self.wfile.write(f"{len(data):x}\r\n".encode()
                                 + data + b"\r\n")

            try:
                for tok in req.iter_tokens(timeout=timeout):
                    _chunk({"token": tok})
                _chunk({"done": True, "id": req.id, "tokens": req.tokens})
            except RuntimeError as e:
                _chunk({"error": str(e)})
            except (queue.Empty, BrokenPipeError, ConnectionResetError):
                # consumer gone or stream stalled: evict the sequence so
                # its blocks free now instead of decoding to a dead pipe
                req.cancel()
                return
            self.wfile.write(b"0\r\n\r\n")

        def do_GET(self):  # noqa: C901
            self._traced(self._get)

        def _get(self):  # noqa: C901
            if self.path == "/metrics":
                # Prometheus exposition: hub counters + native proxy
                # counters + store gauges (SURVEY.md §5 — the reference
                # has no metrics endpoint at all)
                body = metrics.render(proxy=proxy, store=registry.store).encode()
                self._send(200, body, ctype="text/plain; version=0.0.4")
                return
            if self.path.startswith("/debug/telemetry/history"):
                # the durable tier: per-family series reconstructed from
                # the on-disk archive, spanning restarts. Same dep-light
                # stance as the swarm board: an archive can only exist if
                # retention was started (DEMODEL_TELEMETRY_ARCHIVE), so
                # peek sys.modules instead of importing the module
                import sys as _sys
                from urllib.parse import parse_qs, urlsplit

                retention = _sys.modules.get("demodel_tpu.utils.retention")
                archive = retention.current() if retention is not None \
                    else None
                if archive is None:
                    self._send(404, b'{"error":"no telemetry archive '
                                    b'(set DEMODEL_TELEMETRY_ARCHIVE)"}')
                    return
                q = parse_qs(urlsplit(self.path).query)

                def _qs(key):
                    v = q.get(key, [None])[0]
                    return v if v else None

                def _qf(key):
                    v = _qs(key)
                    try:
                        return float(v) if v is not None else None
                    except ValueError:
                        return None

                # pick up windows the background flusher hasn't reached
                # yet, so history is current up to this very poll
                archive.flush_once()
                doc = archive.history(  # demodel: allow(metric-hygiene) — the family comes from the query string; an unknown family is an empty (not wrong) series, which is this endpoint's contract
                    family=_qs("family"), label=_qs("label"),
                    since=_qf("since"), until=_qf("until"))
                doc["server"] = "restore"
                self._send(200, json.dumps(doc, default=str).encode())
                return
            if self.path == "/debug/telemetry":
                # the time-series view: 30 s / 5 min sliding-window rates
                # and delta-bucket quantiles over the Python hub, plus the
                # native proxy's scrape-diffed mirror when one is attached
                doc = metrics.telemetry_doc(proxy=proxy)
                doc["server"] = "restore"
                self._send(200, json.dumps(doc, default=str).encode())
                return
            if self.path.startswith("/debug/profile"):
                # the continuous profiler: ?seconds= captures a windowed
                # diff of the always-on aggregate (0 = cumulative), ?hz=
                # temporarily raises the rate, ?format=collapsed|json.
                # utils.profiler is stdlib-only, so a direct import keeps
                # the node dep-light; DEMODEL_OBS=0 → 503 (tier is off).
                from urllib.parse import parse_qs, urlsplit

                from demodel_tpu.utils import profiler

                q = parse_qs(urlsplit(self.path).query)

                def _qp(key, default, cast):
                    v = q.get(key, [None])[0]
                    try:
                        return cast(v) if v else default
                    except ValueError:
                        return default

                seconds = _qp("seconds", 1.0, float)
                hz = _qp("hz", 0, int)
                fmt = _qp("format", "json", str)
                prof = profiler.capture(seconds=seconds, hz=hz)
                if prof is None:
                    self._send(503, b'{"error":"profiler disabled '
                                    b'(DEMODEL_OBS=0)"}')
                    return
                prof["server"] = "restore"
                if fmt == "collapsed":
                    self._send(200, profiler.collapse(prof).encode(),
                               ctype="text/plain; charset=utf-8")
                else:
                    self._send(200,
                               json.dumps(prof, default=str).encode())
                return
            if self.path == "/debug/statusz":
                # live introspection: open breakers, budget charge,
                # in-flight span tree, flight-recorder state — "what is
                # this node doing right now", from curl
                from demodel_tpu.utils import statusz

                doc = statusz.snapshot(extra={
                    "server": "restore",
                    "models": registry.models(),
                })
                self._send(200, json.dumps(doc, default=str).encode())
                return
            if self.path == "/restore/models":
                self._send(200, json.dumps({"models": registry.models()}).encode())
                return
            m = re.match(r"^/swarm/([^/]+)/([^/]+)/chunks$", self.path)
            if m:
                board = _swarm_board(m.group(1), m.group(2))
                if board is None:
                    self._send(404, b'{"error":"no such swarm board"}')
                    return
                self._send(200, json.dumps(board.summary()).encode())
                return
            m = re.match(r"^/swarm/([^/]+)/([^/]+)/chunk/([^/]+)/(\d+)$",
                         self.path)
            if m:
                board = _swarm_board(m.group(1), m.group(2))
                data = board.get(m.group(3), int(m.group(4))) \
                    if board is not None else None
                if data is None:
                    self._send(404, b'{"error":"chunk not held"}')
                    return
                metrics.HUB.inc("swarm_chunks_served_total")
                metrics.HUB.inc("swarm_bytes_served_total", len(data))
                self._send(200, data, ctype="application/octet-stream")
                return
            m = re.match(r"^/restore/blob/([0-9a-f]{64})$", self.path)
            if m:
                # dedup probe of the streamed save: 200 = skip the upload
                if registry.has_tensor_blob(m.group(1)):
                    self._send(200, b'{"present":true}')
                else:
                    self._send(404, b'{"present":false}')
                return
            m = re.match(r"^/restore/(.+)/manifest$", self.path)
            if m:
                manifest = registry.manifest(
                    m.group(1), request_host=self.headers.get("Host"))
                if manifest is None:
                    self._send(404, b'{"error":"model not registered"}')
                    return
                self._send(200, json.dumps(manifest).encode())
                return
            m = re.match(r"^/restore/(.+)/tensor/(.+)$", self.path)
            if m:
                loc = registry.locate(m.group(1), m.group(2))
                if loc is None:
                    self._send(404, b'{"error":"no such tensor"}')
                    return
                off, length, status = 0, loc.nbytes, 200
                extra = {"Accept-Ranges": "bytes"}
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    # RFC 9110 §14.2: an unparsable Range is ignored; a
                    # parsable-but-unsatisfiable one (past-end start,
                    # reversed, zero suffix) gets 416
                    try:
                        a, _, b = rng[6:].partition("-")
                        if a:
                            off = int(a)
                            end = int(b) if b else loc.nbytes - 1
                        else:
                            n = int(b)
                            if n <= 0:
                                self._send(416, b"")
                                return
                            off = max(0, loc.nbytes - n)
                            end = loc.nbytes - 1
                    except ValueError:
                        off, end = 0, loc.nbytes - 1
                    else:
                        if off >= loc.nbytes or end < off:
                            self._send(416, b"")
                            return
                        end = min(end, loc.nbytes - 1)
                        status = 206
                        extra["Content-Range"] = f"bytes {off}-{end}/{loc.nbytes}"
                    length = end - off + 1
                body = registry.store.pread(loc.key, length, loc.start + off)
                metrics.HUB.inc("restore_tensor_requests_total")
                metrics.HUB.inc("restore_bytes_total", len(body))
                self._send(status, body, ctype="application/octet-stream", extra=extra)
                return
            self._send(404, b'{"error":"not found"}')

    return RestoreHandler


class RestoreServer:
    """Threaded HTTP server over a RestoreRegistry. ``proxy`` (optional)
    adds the native data-plane counters to ``/metrics``."""

    def __init__(self, registry: RestoreRegistry, host: str = "0.0.0.0",
                 port: int = 0, proxy=None):
        self.registry = registry
        self._proxy = proxy
        self.httpd = ThreadingHTTPServer((host, port), make_handler(registry, proxy))
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)

    def start(self) -> "RestoreServer":
        self._thread.start()
        # durable telemetry rides the serving node: only when the archive
        # knob is set does the retention module get imported/started at
        # all — unset leaves this path byte-identical to a tree without it
        from demodel_tpu.utils.env import telemetry_archive_dir

        if telemetry_archive_dir():
            from demodel_tpu.utils import retention

            retention.ensure(proxy=self._proxy)
        # the continuous profiler is always-on at the observe tier (a
        # serving node must be profilable from curl without a restart);
        # DEMODEL_OBS=0 makes this a no-op — no thread ever starts
        from demodel_tpu.utils import profiler

        profiler.ensure()
        # background scrubber: same opt-in stance as retention — only a
        # node with DEMODEL_SCRUB_INTERVAL_SECS set pays the import or
        # the thread; off (the default) leaves this path inert
        from demodel_tpu.utils.env import scrub_interval_secs

        if scrub_interval_secs() > 0:
            from demodel_tpu import scrub

            scrub.ensure(self.registry.store)
        log.info("restore API listening on :%d", self.port)
        return self

    def stop(self) -> None:
        import sys

        scrub = sys.modules.get("demodel_tpu.scrub")
        if scrub is not None:
            scrub.stop_all()
        self.httpd.shutdown()
        self.httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
