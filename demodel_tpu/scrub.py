"""Background scrubber: low-rate re-digest of committed store objects.

Silent bit-rot in a cached object would otherwise be served until the key
is evicted — the store trusts its commit-time digest forever. The scrubber
walks the committed set in bounded, cursor-resumable slices (the native
``Store::scrub_pass``), re-hashing each object against its recorded
content address and quarantining mismatches (``quarantine/`` move + cache
invalidation), so the next read takes a clean miss and re-fetches.

Knobs (shared with the native proxy's storage maintenance thread — the
surface-parity analyzer keeps the names in lockstep):

- ``DEMODEL_SCRUB_INTERVAL_SECS`` — seconds between slices (0 = off, the
  default: scrubbing is an opt-in for long-lived cache nodes).
- ``DEMODEL_SCRUB_RATE_MB_S`` — re-digest budget; each slice reads at
  most ``rate × interval`` bytes, so verification never contends with
  serving.

Dep-light by design (stdlib + the store wrapper): the restore server
starts one scrubber per store on nodes that never import jax.
"""

from __future__ import annotations

import threading

from demodel_tpu.store import Store
from demodel_tpu.utils import trace
from demodel_tpu.utils.env import scrub_interval_secs, scrub_rate_mb_s
from demodel_tpu.utils.logging import get_logger
from demodel_tpu.utils.metrics import HUB

log = get_logger("scrub")

#: pre-register the scrubber counter families at import so a scrape types
#: them before the first slice runs
HUB.inc("scrub_objects_total", 0)
HUB.inc("scrub_bytes_total", 0)
HUB.inc("scrub_mismatch_total", 0)
HUB.inc("scrub_passes_total", 0)


class Scrubber:
    """One store's background scrub loop: every interval, one bounded
    re-digest slice through the native cursor (mismatches quarantined
    inside the store; counters mirrored into the hub here)."""

    def __init__(self, store: Store):
        self.store = store
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> bool:
        if scrub_interval_secs() <= 0 or self._thread is not None:
            return False
        self._thread = threading.Thread(target=self._run,
                                        name="store-scrub", daemon=True)
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def running(self) -> bool:
        return self._thread is not None

    def _run(self) -> None:
        while not self._stop.wait(scrub_interval_secs()):
            try:
                self.slice()
            except OSError as e:
                # a scrub slice must never kill the loop — the disk it
                # reads is exactly the flaky thing being defended against
                log.warning("scrub slice failed: %s", e)

    def slice(self) -> tuple[bool, int, int, int]:
        """One bounded scrub slice (public for tests and manual kicks).
        Returns ``(wrapped, objects, bytes, mismatched)``."""
        budget = scrub_rate_mb_s() * max(1, scrub_interval_secs()) << 20
        with trace.span("scrub.slice"):
            wrapped, objs, nbytes, mismatched = self.store.scrub(budget)
        HUB.inc("scrub_objects_total", objs)
        HUB.inc("scrub_bytes_total", nbytes)
        if mismatched:
            HUB.inc("scrub_mismatch_total", mismatched)
            # the native scrub quarantines internally (not through
            # Store.quarantine), so mirror the count into the hub family
            HUB.inc("store_quarantined_total", mismatched)
            log.warning("scrub slice quarantined %d corrupt object(s)",
                        mismatched)
        if wrapped:
            HUB.inc("scrub_passes_total")
        return wrapped, objs, nbytes, mismatched


_lock = threading.Lock()
_scrubbers: dict[str, Scrubber] = {}


def ensure(store: Store) -> Scrubber | None:
    """Start (once per store root) the background scrubber when
    ``DEMODEL_SCRUB_INTERVAL_SECS`` > 0; returns None when disabled."""
    if scrub_interval_secs() <= 0:
        return None
    root = str(store.root)
    with _lock:
        sc = _scrubbers.get(root)
        if sc is None:
            sc = Scrubber(store)
            sc.start()
            _scrubbers[root] = sc
        return sc


def stop_all() -> None:
    with _lock:
        scrubbers = list(_scrubbers.values())
        _scrubbers.clear()
    for sc in scrubbers:
        sc.stop()


def snapshot() -> list[dict]:
    """Live scrubber state for the statusz ``storage`` section."""
    with _lock:
        items = sorted(_scrubbers.items())
    return [{"root": root, "running": sc.running(),
             "interval_secs": scrub_interval_secs(),
             "rate_mb_s": scrub_rate_mb_s()} for root, sc in items]
