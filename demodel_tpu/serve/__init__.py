"""Token-serving plane: continuous batching over a paged KV cache.

The workload the distribution stack exists for (ROADMAP item 1): a
model pulled through the swarm/tiered store starts SERVING tokens —
``load_model`` makes a cold boot literally a swarm pull
(:func:`demodel_tpu.delivery.pull_to_hbm` → HBM placement →
:class:`~demodel_tpu.serve.scheduler.GenEngine`), and the engine runs
the vLLM-style loop: paged KV blocks under a tier budget
(:mod:`~demodel_tpu.serve.kvcache`), admit → prefill → interleaved
decode with join-between-steps (:mod:`~demodel_tpu.serve.scheduler`),
503 + Retry-After past the waiting room.

Dep-light contract: this package imports jax (via the model step
functions) and must therefore NEVER be imported by the restore
server/statusz/proxy planes directly — they peek
``sys.modules["demodel_tpu.serve"]`` and mount ``/generate`` (or the
``generation`` statusz section) only when something already booted an
engine, the same discipline the swarm routes use.
"""

from __future__ import annotations

import threading

from demodel_tpu.serve.kvcache import (BlockLease, KVBlockPool,
                                       PoolExhausted)
from demodel_tpu.serve.scheduler import (AdmissionQueue, AdmissionTicket,
                                         GenEngine, QueueOverflow, Request)
from demodel_tpu.utils import trace

__all__ = [
    "AdmissionQueue", "AdmissionTicket", "BlockLease", "GenEngine",
    "KVBlockPool", "PoolExhausted", "QueueOverflow", "Request",
    "boot", "current", "install", "load_model",
]

#: the process-wide engine the HTTP surface serves from (one model per
#: process for now — the restore server's /generate and the statusz
#: ``generation`` section both read this through sys.modules)
_current: GenEngine | None = None
_current_lock = threading.Lock()


def install(engine: GenEngine | None) -> None:
    """Make ``engine`` the process-wide serving engine (None clears);
    a replaced engine keeps running — stopping it is the caller's call."""
    global _current
    with _current_lock:
        _current = engine


def current() -> GenEngine | None:
    with _current_lock:
        return _current


def boot(params, cfg, mesh=None, **engine_kw) -> GenEngine:
    """Start an engine over in-memory params and install it — the
    short path for tests/benches and pre-delivered weights."""
    engine = GenEngine(params, cfg, mesh=mesh, **engine_kw).start()
    install(engine)
    return engine


def load_model(model: str, cfg, *, source: str = "hf",
               revision: str = "main", endpoint: str | None = None,
               mesh=None, peers: list[str] | None = None,
               **engine_kw) -> GenEngine:
    """Cold model boot IS a swarm pull: fetch ``model`` through the
    tiered store / peer plane (:func:`delivery.pull_to_hbm` — cache
    hits serve from disk/RAM tiers, misses ride single-flight), place
    the weights, and start serving them. ``cfg`` is the
    :class:`~demodel_tpu.config.ProxyConfig` naming the store."""
    from demodel_tpu import delivery
    from demodel_tpu.models import auto, llama

    with trace.span("serve.load-model", model=model, source=source):
        report, placed = delivery.pull_to_hbm(
            model, cfg, source=source, revision=revision,
            endpoint=endpoint, mesh=mesh, peers=peers, deliver=True)
        store = delivery.open_store(cfg)
        try:
            _fn, params, mcfg = auto.model_from_pull(
                store, report, mesh=mesh, placement=placed)
        finally:
            store.close()
    if not isinstance(mcfg, llama.LlamaConfig):
        raise ValueError(
            f"serving supports llama-family models; {model!r} resolved "
            f"to {type(mcfg).__name__}")
    engine = GenEngine(params, mcfg, mesh=mesh, model=model,
                       **engine_kw).start()
    install(engine)
    return engine
