"""Paged KV cache: fixed-size blocks in one preallocated host pool.

vLLM's PagedAttention memory discipline grafted onto the repo's tier
accounting: the pool preallocates ``num_blocks`` blocks of
``block_tokens`` KV slots each (all layers of one token position live in
the same block index — a block is ``[L, block_tokens, Hkv, hd]`` ×2 for
K and V), sequences lease whole blocks through a
:class:`~demodel_tpu.tier.TierBudget` so generation KV memory shows up
on statusz next to the RAM tier, and a finished sequence's blocks return
to the free list immediately — no per-sequence ``max_len`` rectangle,
no fragmentation beyond the last partial block.

The model never sees a block table: the scheduler gathers each step's
running sequences into a dense ``[B, S, Hkv, hd]`` view
(:meth:`KVBlockPool.gather`) and writes the step's new K/V back through
:meth:`KVBlockPool.write_token` — placement is entirely the pool's
business, which is what makes admission/eviction a host-side list
operation instead of a device reshape.

Pool arrays are host numpy on purpose: the pool is the *memory ledger*
(alloc/free exactness, budget-bounded admission), while compute shapes
stay static for jit via the scheduler's bucketing. A TPU resident-pool
variant slots in behind the same lease API.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from demodel_tpu.tier import TierBudget
from demodel_tpu.utils.env import gen_block_tokens, gen_kv_mb
from demodel_tpu.utils.logging import get_logger
from demodel_tpu.utils.metrics import HUB

log = get_logger("serve.kvcache")

#: pre-register the generation KV families at import so a scrape types
#: them before the first request (house idiom — see tier.py)
HUB.set_gauge("gen_kv_blocks_in_use", 0)
HUB.inc("gen_kv_blocks_alloc_total", 0)
HUB.inc("gen_kv_blocks_freed_total", 0)


class PoolExhausted(Exception):
    """alloc() asked for more blocks than the pool has free — the
    admission signal: the scheduler keeps the sequence WAITING (or the
    admission queue overflows into 503), it never overcommits."""


class BlockLease:
    """One sequence's blocks. Must reach :meth:`free` exactly once —
    at completion, eviction, or error; idempotent so cleanup paths can
    race shutdown without double-crediting the budget."""

    __slots__ = ("_pool", "blocks", "_freed")

    def __init__(self, pool: "KVBlockPool", blocks: list[int]):
        self._pool = pool
        self.blocks = blocks
        self._freed = False

    def free(self) -> None:
        if self._freed:
            return
        self._freed = True
        self._pool._reclaim(self.blocks)


class KVBlockPool:
    """Preallocated block pool for one model's generation KV.

    ``layers``/``kv_heads``/``head_dim`` fix the block geometry; the
    byte budget (``DEMODEL_GEN_KV_MB`` unless overridden) fixes the
    block count. All block state sits behind one lock; the K/V arrays
    themselves are written lock-free because a block belongs to exactly
    one live lease and only the engine thread touches leased bytes.
    """

    def __init__(self, layers: int, kv_heads: int, head_dim: int, *,
                 block_tokens: int | None = None,
                 budget_mb: int | None = None,
                 dtype: str = "float32"):
        self.block_tokens = int(block_tokens or gen_block_tokens())
        budget_bytes = int(budget_mb if budget_mb is not None
                           else gen_kv_mb()) << 20
        dt = np.dtype(dtype)
        # K + V, every layer, one block of token positions
        self.block_bytes = (2 * layers * self.block_tokens * kv_heads
                            * head_dim * dt.itemsize)
        self.num_blocks = max(1, budget_bytes // self.block_bytes)
        shape = (layers, self.num_blocks, self.block_tokens, kv_heads,
                 head_dim)
        self.k = np.zeros(shape, dt)
        self.v = np.zeros(shape, dt)
        self.budget = TierBudget("gen-kv", budget_bytes)
        self._free_list = list(range(self.num_blocks - 1, -1, -1))
        self._lock = threading.Lock()
        log.info("kv pool: %d blocks x %d tokens (%d KiB/block, %d MiB)",
                 self.num_blocks, self.block_tokens,
                 self.block_bytes >> 10, budget_bytes >> 20)

    # ------------------------------------------------------------ sizing
    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to hold ``tokens`` KV positions (≥1)."""
        return max(1, -(-int(tokens) // self.block_tokens))

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free_list)

    @property
    def in_use_blocks(self) -> int:
        with self._lock:
            return self.num_blocks - len(self._free_list)

    # ------------------------------------------------------- alloc/free
    def alloc(self, n: int) -> BlockLease:
        """Lease ``n`` blocks or raise :class:`PoolExhausted` — never a
        partial grant, so admission is all-or-nothing (no overcommit:
        the caller reserves its worst case up front)."""
        with self._lock:
            if n > len(self._free_list):
                raise PoolExhausted(
                    f"need {n} blocks, {len(self._free_list)} free "
                    f"of {self.num_blocks}")
            blocks = [self._free_list.pop() for _ in range(n)]
            in_use = self.num_blocks - len(self._free_list)
        self.budget.charge(n * self.block_bytes)
        HUB.inc("gen_kv_blocks_alloc_total", n)
        HUB.set_gauge("gen_kv_blocks_in_use", in_use)
        return BlockLease(self, blocks)

    def _reclaim(self, blocks: list[int]) -> None:
        with self._lock:
            self._free_list.extend(blocks)
            in_use = self.num_blocks - len(self._free_list)
        self.budget.release(len(blocks) * self.block_bytes)
        HUB.inc("gen_kv_blocks_freed_total", len(blocks))
        HUB.set_gauge("gen_kv_blocks_in_use", in_use)

    # ---------------------------------------------------------- data IO
    def write_prompt(self, lease: BlockLease, kv) -> None:
        """Page a prefill's KV out into the lease: ``kv`` is the
        per-layer ``(k, v)`` list from ``step_prefill``, each
        [1, T, Hkv, hd]."""
        k = np.stack([np.asarray(lk[0]) for lk, _lv in kv])
        v = np.stack([np.asarray(lv[0]) for _lk, lv in kv])
        T = k.shape[1]
        bs = self.block_tokens
        for j in range(0, T, bs):
            blk = lease.blocks[j // bs]
            n = min(bs, T - j)
            self.k[:, blk, :n] = k[:, j:j + n]
            self.v[:, blk, :n] = v[:, j:j + n]

    def write_token(self, lease: BlockLease, pos: int, k, v) -> None:
        """Write one decoded position: ``k``/``v`` are [L, Hkv, hd]."""
        blk = lease.blocks[pos // self.block_tokens]
        off = pos % self.block_tokens
        self.k[:, blk, off] = k
        self.v[:, blk, off] = v

    def gather(self, leases: list[BlockLease], width: int):
        """Dense [L, B, width, Hkv, hd] K and V views of ``leases`` —
        the per-step ragged batch the model consumes. Rows past a
        sequence's filled length are stale pool bytes; the model masks
        them by length (see ``llama.step_decode``), so short sequences
        simply index block 0 for table slots they don't have."""
        bs = self.block_tokens
        nb = -(-int(width) // bs)
        ids = np.zeros((len(leases), nb), np.int64)
        for i, lease in enumerate(leases):
            got = lease.blocks[:nb]
            ids[i, :len(got)] = got
        L = self.k.shape[0]
        k = self.k[:, ids].reshape(L, len(leases), nb * bs,
                                   *self.k.shape[3:])[:, :, :width]
        v = self.v[:, ids].reshape(L, len(leases), nb * bs,
                                   *self.v.shape[3:])[:, :, :width]
        return k, v

    # ------------------------------------------------------------ intro
    def describe(self) -> dict[str, Any]:
        with self._lock:
            free = len(self._free_list)
        return {
            "block_tokens": self.block_tokens,
            "block_bytes": self.block_bytes,
            "num_blocks": self.num_blocks,
            "free_blocks": free,
            "in_use_blocks": self.num_blocks - free,
            "budget": self.budget.describe(),
        }
