"""Continuous-batching scheduler: admit → prefill → interleaved decode.

The Orca/vLLM serving loop over the paged pool
(:mod:`demodel_tpu.serve.kvcache`): one engine thread advances ALL
running sequences one token per decode step, new sequences join the
running batch *between* steps (a prefill slots in as soon as blocks are
free — no waiting for the batch to drain), and a finished, evicted, or
failed sequence frees its blocks immediately. Admission reserves the
worst case (prompt + ``max_new_tokens``) up front, so a running
sequence can never hit an out-of-blocks wall mid-decode — the
no-overcommit discipline the KV budget exists to enforce.

Backpressure rides the proxy plane's admission contract: a full waiting
queue answers :class:`QueueOverflow`, which the HTTP surface maps to
503 + ``Retry-After`` (``DEMODEL_GEN_RETRY_AFTER``) — loudly rejected,
never silently dropped; every admitted request carries an
:class:`AdmissionTicket` that must settle exactly once.

Compute stays jit-friendly: decode batches are padded to power-of-two
batch/width buckets (padded rows decode with ``length 0`` and are
dropped on the host side), so the number of distinct compiled shapes is
logarithmic in batch size and sequence length.
"""

from __future__ import annotations

import itertools
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Any, Iterator

from demodel_tpu.serve.kvcache import KVBlockPool, PoolExhausted
from demodel_tpu.utils import trace
from demodel_tpu.utils.env import (gen_max_batch, gen_max_new_tokens,
                                   gen_queue_limit, gen_retry_after_s)
from demodel_tpu.utils.logging import get_logger
from demodel_tpu.utils.metrics import HUB, labeled

log = get_logger("serve.scheduler")

#: pre-register the generation families at import (house idiom)
HUB.inc(labeled("gen_tokens_total", stage="prefill"), 0)
HUB.inc(labeled("gen_tokens_total", stage="decode"), 0)
HUB.inc("gen_requests_total", 0)
HUB.inc("gen_rejected_total", 0)
HUB.inc("gen_evicted_total", 0)
HUB.set_gauge("gen_queue_depth", 0)
HUB.set_gauge("gen_running", 0)

_END = object()  # stream sentinel: the request is finished


class QueueOverflow(Exception):
    """Waiting queue is full — the HTTP surface answers 503 with
    ``Retry-After: retry_after`` (the proxy admission contract)."""

    def __init__(self, depth: int, limit: int, retry_after: int):
        super().__init__(
            f"generation queue full ({depth}/{limit} waiting)")
        self.retry_after = retry_after


class Request:
    """One generation request, observable from any thread: a bounded
    stream of generated token ids plus a done event. Tokens-in,
    tokens-out — the plane serves models, not tokenizers."""

    def __init__(self, rid: int, prompt: list[int], max_new_tokens: int):
        self.id = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tokens: list[int] = []
        self.error: str | None = None
        self.ticket: "AdmissionTicket | None" = None
        self.submitted_s = time.time()
        self.started_s: float | None = None
        self.finished_s: float | None = None
        self.done = threading.Event()
        self.cancelled = threading.Event()
        self._stream: queue_mod.Queue = queue_mod.Queue()

    # -- engine side ----------------------------------------------------
    def _emit(self, tok: int) -> None:
        self.tokens.append(tok)
        self._stream.put(tok)

    def _close(self) -> None:
        self.finished_s = time.time()
        self._stream.put(_END)
        self.done.set()

    # -- consumer side --------------------------------------------------
    def cancel(self) -> None:
        """Ask the engine to evict this sequence at the next step
        boundary (its blocks free immediately there)."""
        self.cancelled.set()

    def result(self, timeout: float | None = None) -> list[int]:
        """Block until finished; the generated token ids (raises on a
        failed/evicted request)."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.id} still running")
        if self.error is not None:
            raise RuntimeError(self.error)
        return list(self.tokens)

    def iter_tokens(self, timeout: float = 60.0) -> Iterator[int]:
        """Stream token ids as they are generated; raises on error."""
        while True:
            item = self._stream.get(timeout=timeout)
            if item is _END:
                if self.error is not None:
                    raise RuntimeError(self.error)
                return
            yield item


class AdmissionTicket:
    """One admitted request's slot in the engine's accounting — must
    reach :meth:`finish` exactly once (completion, eviction, or error):
    tickets are how "zero silent drops" is checkable, the outstanding
    count is exactly admitted-minus-settled."""

    __slots__ = ("_queue", "request", "_done")

    def __init__(self, queue: "AdmissionQueue", request: Request):
        self._queue = queue
        self.request = request
        self._done = False

    def finish(self) -> None:
        if self._done:
            return
        self._done = True
        self._queue._settle()


class AdmissionQueue:
    """Bounded waiting room with the proxy's overflow contract."""

    def __init__(self, limit: int, retry_after: int):
        self.limit = int(limit)
        self.retry_after = int(retry_after)
        self._outstanding = 0
        self._settled = 0
        self._lock = threading.Lock()

    def admit(self, request: Request, waiting: int) -> AdmissionTicket:
        """Issue a ticket, or answer the overflow contract when
        ``waiting`` (the scheduler's pending depth) is at the limit."""
        with self._lock:
            if waiting >= self.limit:
                HUB.inc("gen_rejected_total")
                raise QueueOverflow(waiting, self.limit, self.retry_after)
            self._outstanding += 1
        return AdmissionTicket(self, request)

    def _settle(self) -> None:
        with self._lock:
            self._outstanding -= 1
            self._settled += 1

    def describe(self) -> dict[str, Any]:
        with self._lock:
            return {"limit": self.limit, "retry_after_s": self.retry_after,
                    "outstanding": self._outstanding,
                    "settled": self._settled}


class _Seq:
    """Engine-internal running-sequence state."""

    __slots__ = ("req", "lease", "length", "last_tok", "generated")

    def __init__(self, req: Request, lease, length: int, last_tok: int):
        self.req = req
        self.lease = lease
        self.length = length      # KV positions written so far
        self.last_tok = last_tok  # next token to feed
        self.generated = 1        # last_tok itself came from the prefill


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class GenEngine:
    """The serving loop: one thread, one model, one paged pool.

    All cross-thread state (`_pending`, `_running`, `_stop`, token
    counters) is guarded by ``_work``'s lock; the jax arrays and the
    pool's leased bytes are engine-thread-only.
    """

    def __init__(self, params, cfg, mesh=None, *,
                 pool: KVBlockPool | None = None,
                 max_batch: int | None = None,
                 queue_limit: int | None = None,
                 max_new_tokens: int | None = None,
                 block_tokens: int | None = None,
                 kv_mb: int | None = None,
                 model: str = "inline"):
        import jax

        from demodel_tpu.models import llama

        self.params = params
        self.cfg = cfg
        self.mesh = mesh
        self.model = model
        self.pool = pool if pool is not None else KVBlockPool(
            cfg.num_hidden_layers, cfg.num_key_value_heads, cfg.head_dim,
            block_tokens=block_tokens, budget_mb=kv_mb)
        self.max_batch = int(max_batch or gen_max_batch())
        self.max_new_cap = int(max_new_tokens or gen_max_new_tokens())
        self.admission = AdmissionQueue(
            queue_limit if queue_limit is not None else gen_queue_limit(),
            gen_retry_after_s())
        self._jprefill = jax.jit(
            lambda p, t: llama.step_prefill(p, t, cfg, mesh=mesh))
        self._jdecode = jax.jit(
            lambda p, t, c, ln: llama.step_decode(p, t, cfg, c, ln,
                                                  mesh=mesh))
        self._pending: deque[Request] = deque()
        self._running: list[_Seq] = []
        self._stop = False
        self._work = threading.Condition(threading.Lock())
        self._ids = itertools.count(1)
        self._tokens = {"prefill": 0, "decode": 0}
        self.started_s = time.time()
        self._thread = threading.Thread(target=self._run, name="gen-engine",
                                        daemon=True)

    # ------------------------------------------------------------ public
    def start(self) -> "GenEngine":
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the loop and settle every in-flight request (error =
        shutdown) — blocks are freed, tickets finished, streams closed."""
        with self._work:
            self._stop = True
            self._work.notify_all()
        if self._thread.ident is not None:  # tolerate never-started engines
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                # the engine thread is still inside a step (e.g. a long
                # jit compile) and still writing into leased blocks —
                # reclaiming them now would hand corruptible memory to a
                # future engine. Leave all state for the thread to settle
                # when it reaches the stop check.
                with self._work:
                    n_run, n_pend = len(self._running), len(self._pending)
                log.error("engine thread still running after 30s; "
                          "leaving %d leases and %d pending requests "
                          "unreclaimed", n_run, n_pend)
                return
        with self._work:
            leftovers = list(self._pending) + [s.req for s in self._running]
            seqs = list(self._running)
            self._pending.clear()
            self._running.clear()
        for seq in seqs:
            seq.lease.free()
        for req in leftovers:
            self._finish_req(req, error="engine shutdown")
        HUB.set_gauge("gen_queue_depth", 0)
        HUB.set_gauge("gen_running", 0)

    def submit(self, prompt, max_new_tokens: int | None = None) -> Request:
        """Admit one request (greedy decode). Raises
        :class:`QueueOverflow` when the waiting room is full and
        ``ValueError`` on malformed input — both before any KV is
        reserved."""
        toks = [int(t) for t in prompt]
        if not toks:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= self.cfg.vocab_size for t in toks):
            raise ValueError("prompt token out of vocab range")
        want = int(max_new_tokens or self.max_new_cap)
        want = max(1, min(want, self.max_new_cap))
        # a request whose worst-case reservation exceeds the whole pool
        # can NEVER be admitted — and FIFO admission means it would wedge
        # every request behind it. Reject it here (HTTP 400), not in the
        # engine loop.
        need = self.pool.blocks_for(len(toks) + want - 1)
        if need > self.pool.num_blocks:
            raise ValueError(
                f"request needs {need} KV blocks (prompt {len(toks)} + "
                f"{want} new tokens) but the pool only has "
                f"{self.pool.num_blocks}; shorten the prompt or lower "
                f"max_new_tokens")
        req = Request(next(self._ids), toks, want)
        rejected: QueueOverflow | None = None
        with trace.span("serve.admit", request=req.id, prompt=len(toks)):
            with self._work:
                if self._stop:
                    raise RuntimeError("engine stopped")
                try:
                    ticket = self.admission.admit(req, len(self._pending))
                except QueueOverflow as exc:
                    # a full waiting room is an OUTCOME, not an error —
                    # the span records it without tripping the flight
                    # recorder's error-root dump
                    trace.event("rejected", retry_after=exc.retry_after)
                    rejected = exc
                else:
                    req.ticket = ticket
                    self._pending.append(req)
                    # publish while still holding _work so concurrent
                    # submitters can't regress the gauge with a stale depth
                    HUB.inc("gen_requests_total")
                    HUB.set_gauge("gen_queue_depth", len(self._pending))
                    self._work.notify_all()
        if rejected is not None:
            raise rejected
        return req

    def generate(self, prompt, max_new_tokens: int | None = None,
                 timeout: float = 300.0) -> list[int]:
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt, max_new_tokens).result(timeout)

    def describe(self) -> dict[str, Any]:
        with self._work:
            waiting = len(self._pending)
            running = len(self._running)
            tokens = dict(self._tokens)
        return {
            "model": self.model,
            "running": running,
            "waiting": waiting,
            "max_batch": self.max_batch,
            "tokens": tokens,
            "uptime_s": round(time.time() - self.started_s, 3),
            "admission": self.admission.describe(),
            "kv": self.pool.describe(),
        }

    # ------------------------------------------------------ engine loop
    def _run(self) -> None:
        while True:
            with self._work:
                while not self._stop and not self._pending \
                        and not self._running:
                    self._work.wait()
                if self._stop:
                    return
            progressed = False
            while self._admit_one():
                progressed = True
            self._evict_cancelled()
            if self._snapshot_running():
                self._decode_step()
            elif not progressed:
                # pending work exists but nothing could be admitted and
                # nothing is running (shouldn't happen now that submit()
                # rejects over-pool requests, but e.g. a leaked lease
                # could still get here): sleep instead of busy-spinning.
                # submit()/stop() notify; the timeout bounds recovery if
                # a free lands without a notify.
                with self._work:
                    if not self._stop and self._pending \
                            and not self._running:
                        self._work.wait(timeout=0.05)

    def _snapshot_running(self) -> list[_Seq]:
        with self._work:
            return list(self._running)

    def _admit_one(self) -> bool:
        """Move one waiting request into the running batch: reserve its
        worst-case blocks, prefill, emit its first token. False when the
        batch is full, the queue is empty, or blocks are short (head-of-
        line waits for frees — admission order is FIFO, no starvation)."""
        with self._work:
            if self._stop or not self._pending \
                    or len(self._running) >= self.max_batch:
                return False
            req = self._pending[0]
            lease = None
            if not req.cancelled.is_set():
                need = self.pool.blocks_for(
                    len(req.prompt) + req.max_new_tokens - 1)
                try:
                    lease = self.pool.alloc(need)
                except PoolExhausted:
                    return False
                cancelled = True
                try:
                    cancelled = req.cancelled.is_set()
                finally:
                    if cancelled:
                        # cancel landed between the head check and the
                        # alloc — free right here or the blocks/budget
                        # bytes leak forever
                        lease.free()
                        lease = None
            self._pending.popleft()
            depth = len(self._pending)
        HUB.set_gauge("gen_queue_depth", depth)
        if lease is None:
            HUB.inc("gen_evicted_total")
            self._finish_req(req, error="cancelled before start")
            return True
        self._start_seq(req, lease)
        return True

    def _start_seq(self, req: Request, lease) -> None:
        import jax.numpy as jnp
        import numpy as np

        req.started_s = time.time()
        HUB.observe("gen_queue_wait_seconds",
                    req.started_s - req.submitted_s)
        try:
            with trace.span("serve.prefill", request=req.id,
                            prompt=len(req.prompt)):
                tokens = jnp.asarray([req.prompt], jnp.int32)
                logits, kv = self._jprefill(self.params, tokens)
                self.pool.write_prompt(lease, kv)
                tok0 = int(np.argmax(np.asarray(logits[0])))
        except Exception as exc:  # noqa: BLE001 - engine must survive
            lease.free()
            log.error("prefill failed for request %d: %s", req.id, exc)
            self._finish_req(req, error=f"prefill failed: {exc}")
            return
        seq = _Seq(req, lease, len(req.prompt), tok0)
        with self._work:
            self._running.append(seq)
            running = len(self._running)
            self._tokens["prefill"] += len(req.prompt)
        HUB.set_gauge("gen_running", running)
        HUB.inc(labeled("gen_tokens_total", stage="prefill"),
                len(req.prompt))
        req._emit(tok0)
        HUB.inc(labeled("gen_tokens_total", stage="decode"))
        if seq.generated >= req.max_new_tokens:
            self._retire(seq)

    def _evict_cancelled(self) -> None:
        for seq in self._snapshot_running():
            if seq.req.cancelled.is_set():
                HUB.inc("gen_evicted_total")
                self._retire(seq, error="evicted")

    def _decode_step(self) -> None:
        """Advance every running sequence one token, ragged lengths and
        all — the continuous-batching inner loop."""
        import jax.numpy as jnp
        import numpy as np

        batch = self._snapshot_running()
        if not batch:
            return
        B = len(batch)
        Bb = _pow2(B)
        bs = self.pool.block_tokens
        width = bs * _pow2(-(-max(s.length for s in batch) // bs))
        toks = np.zeros((Bb,), np.int32)
        lens = np.zeros((Bb,), np.int32)
        for i, s in enumerate(batch):
            toks[i] = s.last_tok
            lens[i] = s.length
        k, v = self.pool.gather([s.lease for s in batch], width)
        if Bb > B:  # pad rows ride along with length 0 and are dropped
            pad = ((0, 0), (0, Bb - B)) + ((0, 0),) * (k.ndim - 2)
            k = np.pad(k, pad)
            v = np.pad(v, pad)
        cache = [(jnp.asarray(k[li]), jnp.asarray(v[li]))
                 for li in range(k.shape[0])]
        try:
            with trace.span("serve.decode-step", batch=B, width=width):
                logits, new_kv = self._jdecode(
                    self.params, jnp.asarray(toks), cache,
                    jnp.asarray(lens))
                out = np.asarray(logits)
                nk = np.stack([np.asarray(lk[:, 0]) for lk, _lv in new_kv])
                nv = np.stack([np.asarray(lv[:, 0]) for _lk, lv in new_kv])
        except Exception as exc:  # noqa: BLE001 - engine must survive
            log.error("decode step failed (batch=%d): %s", B, exc)
            for seq in batch:
                self._retire(seq, error=f"decode failed: {exc}")
            return
        done = 0
        for i, seq in enumerate(batch):
            self.pool.write_token(seq.lease, seq.length, nk[:, i], nv[:, i])
            seq.length += 1
            tok = int(np.argmax(out[i]))
            seq.last_tok = tok
            seq.generated += 1
            seq.req._emit(tok)
            if seq.generated >= seq.req.max_new_tokens:
                self._retire(seq)
                done += 1
        with self._work:
            self._tokens["decode"] += B
        HUB.inc(labeled("gen_tokens_total", stage="decode"), B)

    def _retire(self, seq: _Seq, error: str | None = None) -> None:
        """Finished/evicted/failed: blocks free IMMEDIATELY (the next
        _admit_one can use them this very iteration)."""
        seq.lease.free()
        with self._work:
            if seq in self._running:
                self._running.remove(seq)
            running = len(self._running)
        HUB.set_gauge("gen_running", running)
        self._finish_req(seq.req, error=error)

    def _finish_req(self, req: Request, error: str | None = None) -> None:
        req.error = error
        if req.ticket is not None:
            req.ticket.finish()
        req._close()
