from demodel_tpu.sink.hbm import (
    Placement,
    deliver_gguf,
    deliver_report_to_hbm,
    deliver_safetensors,
    place_tensor,
)
from demodel_tpu.sink.plan import ShardingPlan
from demodel_tpu.sink.remote import PeerBlobReader, pull_manifest_to_hbm
from demodel_tpu.sink.streaming import StreamingSink

__all__ = ["Placement", "deliver_gguf", "deliver_report_to_hbm",
           "deliver_safetensors", "place_tensor", "PeerBlobReader",
           "pull_manifest_to_hbm", "ShardingPlan", "StreamingSink"]
