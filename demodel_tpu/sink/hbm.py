"""HBM sink: stream tensors from the chunk store into sharded device arrays.

The north-star component (``BASELINE.json``): where the reference's delivery
ends at cached bytes on disk, this sink parses safetensors/GGUF byte ranges
out of the content-addressed store and lands each tensor *shard-wise* in
device memory under a ``NamedSharding``:

- per-device byte ranges: a tensor split on its leading axis is contiguous
  in the file, so each device's shard is a single range read — no host copy
  of the whole checkpoint, and on multi-host meshes each host reads only its
  addressable shards;
- quantized GGUF tensors are dequantized on-device (pallas kernels in
  :mod:`demodel_tpu.ops.dequant`), shard-wise when block boundaries allow,
  so the host→device link carries the small quantized payload;
- assembled with ``jax.make_array_from_single_device_arrays`` — the jit-ready
  global array, no resharding pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from demodel_tpu.formats import gguf as gguf_mod
from demodel_tpu.formats import safetensors as st
from demodel_tpu.formats.safetensors import _np_dtype  # shared dtype table
from demodel_tpu.ops import dequant
from demodel_tpu.parallel.mesh import make_mesh
from demodel_tpu.sink.plan import ShardingPlan
from demodel_tpu.store import Store
from demodel_tpu.utils.logging import get_logger

log = get_logger("sink")


@dataclass
class Placement:
    arrays: dict[str, jax.Array] = field(default_factory=dict)
    mesh_desc: str = ""
    #: background finalizer thread (deferred cache commit + manifest) set by
    #: ``pull_to_hbm(defer_cache_commit=True)`` — join via :meth:`finalize`
    finalizer: object = None
    #: ``[(key, error)]`` from the deferred cache commits (set by the
    #: finalizer); ``integrity_errors`` ⊆ ``commit_errors`` are re-hash
    #: mismatches proving the DELIVERED bytes corrupt
    commit_errors: list = field(default_factory=list)
    integrity_errors: list = field(default_factory=list)
    #: exception the finalizer itself died with (e.g. the manifest write
    #: failed) — re-raised by :meth:`finalize`
    finalize_error: object = None
    #: delivery phase wall-clock split (``fetch_secs``/``place_secs``, or
    #: ``fetch_stall_secs`` under prefetch overlap) set by the pipelined
    #: sharded path — the network-bound vs device-transfer-bound diagnosis
    phase_secs: dict | None = None

    @property
    def total_bytes(self) -> int:
        return sum(int(a.nbytes) for a in self.arrays.values())

    def finalize(self, timeout: float | None = None) -> None:
        """Join the deferred persistence work (cache commits, manifest,
        store close). Raises when optimistic verification found delivered
        bytes corrupt — the arrays in this placement must be discarded and
        re-pulled. No-op when delivery was not deferred."""
        if self.finalizer is not None:
            self.finalizer.join(timeout)
            if self.finalizer.is_alive():
                raise TimeoutError(
                    f"delivery finalizer still running after {timeout}s")
        if self.integrity_errors:
            raise IOError("delivered bytes failed digest verification; "
                          f"discard this placement: {self.integrity_errors}")
        if self.finalize_error is not None:
            raise IOError("delivery finalization failed (cache/manifest "
                          "not persisted)") from self.finalize_error


def _slices_contiguous_rows(idx: tuple, shape: tuple[int, ...]) -> tuple[int, int] | None:
    """If ``idx`` selects whole trailing dims and a row range on axis 0,
    return (row_start, row_stop); else None."""
    if not shape:
        return None
    first = idx[0] if idx else slice(None)
    rest = idx[1:] if len(idx) > 1 else ()
    for i, s in enumerate(rest):
        full = s == slice(None) or (
            isinstance(s, slice)
            and (s.start in (0, None))
            and (s.stop in (None, shape[i + 1]))
        )
        if not full:
            return None
    if first == slice(None):
        return 0, shape[0]
    if isinstance(first, slice):
        start = first.start or 0
        stop = first.stop if first.stop is not None else shape[0]
        return start, stop
    return None


def _fully_replicated(sharding: NamedSharding) -> bool:
    return all(p is None for p in sharding.spec) or len(sharding.spec) == 0


def place_tensor(
    read_at,
    shape: tuple[int, ...],
    np_dtype,
    start: int,
    sharding: NamedSharding,
    cast_to=None,
    read_into=None,
    ici_complete: bool = False,
) -> jax.Array:
    """Build a sharded global array reading only per-device byte ranges.

    ``read_at(offset, length)`` serves file-absolute ranges; ``start`` is the
    tensor's first data byte. Axis-0 (and replicated) shards are contiguous
    single-range reads; other layouts fall back to one host read of the
    tensor, sliced per device. When ``read_into(offset, out_buffer)`` is
    given, range reads land straight in the numpy buffer handed to
    ``device_put`` — one copy instead of two.

    ``ici_complete`` (SURVEY.md §2.3 "Intra-pod shard exchange"): a
    REPLICATED tensor on a multi-host mesh would make every host read every
    byte over disk/DCN. Instead each host loads only its 1/N of the rows
    (staged row-sharded) and an XLA all-gather over ICI completes the
    replicas — each byte crosses the slow path exactly once.
    """
    itemsize = np.dtype(np_dtype).itemsize
    mesh = sharding.mesh
    n_total = int(np.prod(list(mesh.shape.values()), dtype=np.int64))
    if (ici_complete and _fully_replicated(sharding) and shape
            and shape[0] % n_total == 0
            and int(np.prod(shape, dtype=np.int64)) * itemsize
            >= 4096 * n_total):
        stage = NamedSharding(
            mesh, P(tuple(mesh.axis_names), *([None] * (len(shape) - 1))))
        staged = place_tensor(read_at, shape, np_dtype, start, stage,
                              cast_to, read_into=read_into)
        from demodel_tpu.parallel.collectives import redistribute

        return redistribute(staged, sharding)
    row_bytes = int(np.prod(shape[1:], dtype=np.int64)) * itemsize if shape else itemsize
    dev_map = sharding.addressable_devices_indices_map(shape)

    def read_range(offset: int, nbytes: int, out_shape) -> np.ndarray:
        if read_into is not None:
            # allocate flat and read through a uint8 view: exotic dtypes
            # (ml_dtypes.bfloat16) have no buffer-protocol format, and 0-d
            # arrays cannot be re-viewed — both work via the flat buffer
            flat = np.empty(nbytes // itemsize, dtype=np_dtype)
            got = read_into(offset, flat.view(np.uint8))
            if got != nbytes:
                raise IOError(f"short read: {got} != {nbytes}")
            return flat.reshape(out_shape)
        return np.frombuffer(read_at(offset, nbytes), dtype=np_dtype).reshape(out_shape)

    whole: np.ndarray | None = None
    shards = []
    cache: dict[tuple[int, int], np.ndarray] = {}
    for device, idx in dev_map.items():
        rows = _slices_contiguous_rows(idx, shape)
        if rows is not None:
            r0, r1 = rows
            if (r0, r1) in cache:
                arr = cache[(r0, r1)]
            else:
                arr = read_range(start + r0 * row_bytes, (r1 - r0) * row_bytes,
                                 (r1 - r0,) + shape[1:])
                cache[(r0, r1)] = arr
        else:
            if whole is None:
                total = int(np.prod(shape, dtype=np.int64)) * itemsize
                whole = read_range(start, total, shape)
            arr = whole[idx]
            if not arr.flags["C_CONTIGUOUS"]:  # keep 0-d shape: as-contig
                arr = np.ascontiguousarray(arr)  # would promote () to (1,)
        if cast_to is not None and arr.dtype != np.dtype(cast_to):
            arr = arr.astype(cast_to)
        shards.append(jax.device_put(arr, device))
    return jax.make_array_from_single_device_arrays(shape, sharding, shards)


# ------------------------------------------------------------- safetensors


def _ici_complete_default() -> bool:
    """On multi-host runs, replicated tensors complete over ICI by default
    (each host reads 1/N); DEMODEL_ICI_COMPLETE forces either way."""
    import os

    env = os.environ.get("DEMODEL_ICI_COMPLETE", "").strip().lower()
    if env in ("1", "true", "yes", "on"):
        return True
    if env in ("0", "false", "no", "off"):
        return False
    return jax.process_count() > 1


def deliver_safetensors(
    store: Store,
    key: str,
    mesh: Mesh | None = None,
    plan: ShardingPlan | None = None,
    cast_to=None,
    buffer=None,
    ici_complete: bool | None = None,
    skip: set | None = None,
) -> Placement:
    """Land every tensor of a stored safetensors blob in HBM, sharded.

    With ``buffer`` (a bytes-like landing buffer from
    :meth:`~demodel_tpu.parallel.peer.PeerSet.fetch_to_memory`), tensor
    ranges are zero-copy views of host memory — no disk read on the
    delivery path. ``skip`` names tensors already placed (a failed
    pipelined attempt's survivors): their windows are neither fetched
    nor re-transferred."""
    if mesh is None:
        mesh = make_mesh()
    if plan is None:
        plan = ShardingPlan(mesh)
    if buffer is not None:
        mv = memoryview(buffer)
        read_at = lambda off, ln: mv[off:off + ln]  # noqa: E731 — zero-copy
        read_into = None
        index = st.read_index_from(
            lambda off, ln: bytes(mv[off:off + ln]), total_size=len(mv))
    else:
        read_at = lambda off, ln: store.pread(key, ln, off)  # noqa: E731
        read_into = lambda off, out: store.pread_into(key, out, off)  # noqa: E731
        index = st.read_index_from(read_at, total_size=store.size(key))
    if ici_complete is None:
        ici_complete = _ici_complete_default()
    if buffer is not None:
        # memory-first delivery: the FULL file is already in this host's
        # RAM, so a staged load + all-gather would re-move bytes the host
        # has — the ICI leg only pays when reads hit the slow path
        ici_complete = False
    out = Placement(mesh_desc=f"{dict(mesh.shape)}")
    for name, spec in index.tensors.items():
        if skip and name in skip:
            continue
        np_dtype = _np_dtype(spec.dtype)
        sharding = plan.sharding_for(name, spec.shape, np_dtype.itemsize)
        out.arrays[name] = place_tensor(
            read_at, spec.shape, np_dtype, spec.start, sharding, cast_to,
            read_into=read_into, ici_complete=ici_complete,
        )
    return out


# -------------------------------------------------------------------- gguf


def _dequant_shard(t: gguf_mod.GGUFTensor, raw: bytes, shape, out_dtype, device):
    decoded = gguf_mod.decode_raw(
        gguf_mod.GGUFTensor(t.name, t.ggml_type, shape, 0, len(raw)), raw
    )
    if t.ggml_type in (gguf_mod.GGML_F32, gguf_mod.GGML_F16):
        return jax.device_put(np.asarray(decoded), device).astype(out_dtype)
    parts = [jax.device_put(p, device) for p in decoded]
    fn = {
        gguf_mod.GGML_Q8_0: dequant.dequant_q8_0,
        gguf_mod.GGML_Q4_0: dequant.dequant_q4_0,
        gguf_mod.GGML_Q2_K: dequant.dequant_q2_k,
        gguf_mod.GGML_Q3_K: dequant.dequant_q3_k,
        gguf_mod.GGML_Q4_K: dequant.dequant_q4_k,
        gguf_mod.GGML_Q5_K: dequant.dequant_q5_k,
        gguf_mod.GGML_Q6_K: dequant.dequant_q6_k,
    }[t.ggml_type]
    flat = fn(*parts, out_dtype)
    return flat.reshape(shape)


def deliver_gguf(
    store: Store,
    key: str,
    mesh: Mesh | None = None,
    plan: ShardingPlan | None = None,
    out_dtype=jnp.bfloat16,
    buffer=None,
) -> Placement:
    """Land a GGUF blob's tensors in HBM as ``out_dtype`` (dequantized
    on-device, shard-wise when each device's rows align to quant blocks)."""
    if mesh is None:
        mesh = make_mesh()
    if plan is None:
        plan = ShardingPlan(mesh)
    if buffer is not None:
        mv = memoryview(buffer)
        read_at = lambda off, ln: bytes(mv[off:off + ln])  # noqa: E731
    else:
        read_at = lambda off, ln: store.pread(key, ln, off)  # noqa: E731
    index = gguf_mod.read_index_from(read_at)
    out = Placement(mesh_desc=f"{dict(mesh.shape)}")
    # (elements per quant block, bytes per block)
    block_geom = {
        gguf_mod.GGML_Q8_0: (gguf_mod.QK, gguf_mod.Q8_0_BLOCK_BYTES),
        gguf_mod.GGML_Q4_0: (gguf_mod.QK, gguf_mod.Q4_0_BLOCK_BYTES),
        gguf_mod.GGML_F32: (1, 4),
        gguf_mod.GGML_F16: (1, 2),
        **{g: (gguf_mod.QK_K, bpb) for g, bpb in gguf_mod.K_BLOCK_BYTES.items()},
    }
    for name, t in index.tensors.items():
        sharding = plan.sharding_for(name, t.shape, 2)
        row_elems = int(np.prod(t.shape[1:], dtype=np.int64)) if len(t.shape) > 1 else 1
        blk_elems, bpb = block_geom[t.ggml_type]
        # shard-wise dequant needs each row range to start/end on a quant
        # block boundary (32 elems for Q*_0, 256 for K-quants)
        per_shard_ok = t.shape and row_elems % blk_elems == 0
        dev_map = sharding.addressable_devices_indices_map(t.shape)
        shards, ok = [], True
        if per_shard_ok:
            row_bytes = row_elems // blk_elems * bpb
            cache: dict[tuple[int, int], bytes] = {}
            for device, idx in dev_map.items():
                rows = _slices_contiguous_rows(idx, t.shape)
                if rows is None:
                    ok = False
                    break
                r0, r1 = rows
                raw = cache.get((r0, r1))
                if raw is None:
                    raw = read_at(t.start + r0 * row_bytes, (r1 - r0) * row_bytes)
                    cache[(r0, r1)] = raw
                shard_shape = (r1 - r0,) + t.shape[1:]
                shards.append(_dequant_shard(t, raw, shard_shape, out_dtype, device))
            if ok:
                out.arrays[name] = jax.make_array_from_single_device_arrays(
                    t.shape, sharding, shards
                )
                continue
        # fallback: whole-tensor dequant then reshard
        raw = read_at(t.start, t.nbytes)
        arr = dequant.dequant_gguf_tensor(t, gguf_mod.decode_raw(t, raw), out_dtype)
        out.arrays[name] = jax.device_put(arr, sharding)
    return out


# ------------------------------------------------------------------ report


def is_weight_file(name: str, media_type: str = "") -> bool:
    """Artifacts the HBM sink delivers (shared with the streaming sink)."""
    return (
        name.endswith(".safetensors")
        or name.endswith(".gguf")
        or media_type == "application/vnd.ollama.image.model"
    )


def deliver_file(store: Store, name: str, key: str, mesh: Mesh,
                 plan: ShardingPlan, cast_to=None, buffer=None,
                 ici_complete: bool | None = None) -> Placement:
    """Deliver one weight file (dispatch by format). Shared by the
    non-streaming and streaming sinks so dispatch rules never diverge.
    ``buffer`` short-circuits the store read (memory-first delivery).

    The STREAMING sink must pass ``ici_complete=False``: its per-file
    delivery order follows fetch completion, which differs across hosts,
    and multi-controller collectives pair by launch order — only ordered
    delivery passes (:func:`deliver_report_to_hbm`) may use the ICI leg."""
    if name.endswith(".safetensors"):
        return deliver_safetensors(store, key, mesh, plan, cast_to,
                                   buffer=buffer, ici_complete=ici_complete)
    return deliver_gguf(store, key, mesh, plan, buffer=buffer)


def merge_placement(dst: Placement, placed: Placement) -> None:
    """Merge one file's tensors into the running placement, rejecting
    duplicate tensor names across shards."""
    overlap = set(dst.arrays) & set(placed.arrays)
    if overlap:
        raise ValueError(f"duplicate tensors across shards: {sorted(overlap)[:3]}")
    dst.arrays.update(placed.arrays)


def deliver_report_to_hbm(store: Store, report, mesh: Mesh | None = None,
                          plan: ShardingPlan | None = None) -> Placement:
    """Deliver every weight artifact of a PullReport into HBM (non-streaming
    form of :mod:`demodel_tpu.sink.streaming` — for already-pulled reports)."""
    if mesh is None:
        mesh = make_mesh()
    if plan is None:
        plan = ShardingPlan(mesh)
    files = report.files if hasattr(report, "files") else report["files"]
    out = Placement(mesh_desc=f"{dict(mesh.shape)}")
    for f in files:
        name = f.name if hasattr(f, "name") else f["name"]
        key = f.key if hasattr(f, "key") else f["key"]
        media = f.media_type if hasattr(f, "media_type") else f.get("media_type", "")
        if not is_weight_file(name, media):
            continue
        merge_placement(out, deliver_file(store, name, key, mesh, plan))
    log.info("delivered %d tensors (%.1f MB) onto mesh %s",
             len(out.arrays), out.total_bytes / 1e6, out.mesh_desc)
    return out
