"""Sharding plan: tensor name/shape → NamedSharding for delivery.

The delivery-time analogue of a model's parallelism plan (SURVEY.md §2.3
"Sharded HBM placement"): weight matrices shard on their leading axis over
``tp`` (contiguous in safetensors/GGUF files, so every device's shard is a
single range read); small tensors (biases, norms, scalars) replicate. A
consumer with an exact layout (e.g. the Orbax network restore) passes its
own shardings instead — the plan is the default, not a constraint.
"""

from __future__ import annotations

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from demodel_tpu.utils.env import env_int


class ShardingPlan:
    """Default placement rules over a mesh's ``tp`` axis.

    ``min_shard_bytes``: tensors smaller than this replicate — sharding a
    128-byte layernorm wastes more in dispatch than it saves in HBM
    (override via ``DEMODEL_MIN_SHARD_KB``).
    """

    def __init__(self, mesh: Mesh, min_shard_bytes: int | None = None):
        self.mesh = mesh
        self.tp = int(mesh.shape.get("tp", 1))
        if min_shard_bytes is None:
            min_shard_bytes = env_int("DEMODEL_MIN_SHARD_KB", 4, minimum=0) << 10
        self.min_shard_bytes = min_shard_bytes

    def sharding_for(self, name: str, shape: tuple[int, ...],
                     itemsize: int) -> NamedSharding:
        del name  # rules are shape-driven; name kept for subclass overrides
        nbytes = itemsize
        for d in shape:
            nbytes *= int(d)
        if (len(shape) >= 2 and self.tp > 1 and shape[0] % self.tp == 0
                and nbytes >= self.min_shard_bytes):
            return NamedSharding(
                self.mesh, P("tp", *([None] * (len(shape) - 1))))
        return NamedSharding(self.mesh, P())
