"""Sharded pod delivery: place a checkpoint over the peer HTTP plane,
reading ONLY the byte ranges this host's devices need.

This is the composed "peer shard cache across pod hosts over ICI/DCN"
flow (`/root/reference/README.md:5-10`; SURVEY.md §2.3): where the whole-
file pull path copies every weight byte to every host, this path drives
:func:`~demodel_tpu.sink.hbm.deliver_safetensors` against a reader whose
``pread``/``pread_into`` are HTTP **Range** requests on a warm peer's
``/peer/object/{key}`` endpoint:

- a tensor sharded on axis 0 → each host fetches only its devices'
  contiguous row windows over DCN (native multi-stream window fan-out,
  socket reads landing directly in the ``device_put`` buffer);
- a replicated tensor with ``ici_complete`` → each host fetches 1/N of
  the rows, one XLA all-gather over ICI completes the replicas — every
  byte crosses the slow (DCN) path exactly once for the whole pod;
- delivery walks the model manifest in manifest order on every host, so
  the multi-controller collectives pair deterministically (the ordering
  problem that forces the streaming sink to disable ``ici_complete``,
  `sink/streaming.py`, does not exist here by construction).

The model manifest itself is discovered on the peer (the pull path
publishes a ``demodel://models/{source}/{model}`` record, so a cold pod
host needs NO registry round-trip at all — the warm peer is the source
of truth, matching the reference's "serve your friends" story).
"""

from __future__ import annotations

import ctypes
import json
import os
import threading
import time

import numpy as np
import requests

from demodel_tpu.delivery import manifest_key
from demodel_tpu.parallel import placement as swarm_placement
from demodel_tpu.parallel.placement import (
    ChunkBoard,
    HashRing,
    bitmap_indices,
    bounded_assign,
    chunk_count,
    chunk_span,
    default_chunk_bytes,
)
from demodel_tpu.sink.hbm import Placement, is_weight_file, merge_placement
from demodel_tpu.sink.plan import ShardingPlan
from demodel_tpu.utils import metrics, trace
from demodel_tpu.utils.env import env_int
from demodel_tpu.utils.faults import (
    PeerHealth,
    RangeIgnored,
    RetryPolicy,
    TruncatedBody,
    WireError,
    count_retry,
    peer_cannot_serve,
    request_with_retry,
    retryable,
)
from demodel_tpu.utils.logging import get_logger

log = get_logger("sink.remote")

#: window reads at/under this ride one pooled requests connection; larger
#: windows fan out over native range streams (connection setup ~free vs
#: the transfer beyond this size)
_NATIVE_MIN_BYTES = 4 << 20


class WindowAbort(IOError):
    """A window transfer died mid-body. ``got`` bytes already landed in
    the caller's buffer (real network bytes, never re-fetched); ``cause``
    carries the transport error for retry classification."""

    def __init__(self, got: int, cause: BaseException):
        super().__init__(str(cause))
        self.got = got
        self.cause = cause


class PeerBlobReader:
    """Store-shaped reads (``size``/``pread``/``pread_into``) served by
    HTTP Range requests against one object on one peer.

    Duck-types the subset of :class:`~demodel_tpu.store.Store` that
    :func:`~demodel_tpu.sink.hbm.deliver_safetensors` touches, so the
    whole sharded-placement machinery (per-device windows, ici staging,
    GGUF dispatch) runs unchanged over the wire. Thread-safe; counts
    ``bytes_fetched`` for the pod-delivery proof ("each host reads < the
    whole checkpoint").

    Window-level recovery: a failed Range read resumes at the exact
    received offset — first on the next healthy ``failover`` peer holding
    the same key (breaker-gated via the shared :class:`PeerHealth`), with
    backoff when no alternative exists — so one RST at shard 14/15 costs
    one re-issued window remainder, not the pipeline.
    """

    def __init__(self, peer: str, remote_key: str, size: int,
                 session: requests.Session | None = None,
                 streams: int | None = None, timeout: float | None = None,
                 path: str | None = None,
                 failover: list[str] | None = None,
                 health: PeerHealth | None = None,
                 policy: RetryPolicy | None = None):
        self.remote_key = remote_key
        #: served resource path — /peer/object/{key} by default; the
        #: restore client points this at /restore/{model}/tensor/{name}
        #: (same Range semantics on the native plane)
        self.path = path or f"/peer/object/{remote_key}"
        self._size = int(size)
        self.timeout = timeout if timeout is not None else float(
            env_int("DEMODEL_PEER_TIMEOUT", 120, minimum=1))
        from demodel_tpu.parallel.peer import _peer_streams

        self.streams = streams if streams is not None else _peer_streams()
        self._tls = threading.local()
        self._session = session
        self.bytes_fetched = 0
        self._count_lock = threading.Lock()
        first = peer.rstrip("/")
        self._peers = [first] + [q for q in
                                 (p.rstrip("/") for p in (failover or []))
                                 if q != first]
        self._health = health if health is not None else PeerHealth.shared()
        self._policy = policy if policy is not None else RetryPolicy()
        #: guards peer/_native_host/_native_port against torn reads —
        #: concurrent pread_into calls share this reader and one thread's
        #: failover must not hand another thread host A with port B
        self._peer_lock = threading.Lock()
        self._set_peer(first)

    def _set_peer(self, peer: str) -> None:
        import re as _re

        m = _re.match(r"^http://(\[[0-9a-fA-F:]+\]|[^:/]+)(?::(\d+))?$",
                      peer)
        with self._peer_lock:
            self.peer = peer
            # https/odd peers: every read takes the requests path
            self._native_host = m.group(1).strip("[]") if m else None
            self._native_port = int(m.group(2) or 80) if m else 0

    def _snapshot(self) -> tuple[str, str | None, int]:
        """A consistent (peer, native_host, native_port) for one attempt."""
        with self._peer_lock:
            return self.peer, self._native_host, self._native_port

    def _fail_over(self, from_peer: str,
                   exclude: set | frozenset = frozenset()) -> bool:
        """Rotate to the next breaker-admitted peer holding this key
        (skipping ``exclude`` — peers proven unable to serve this
        object). Returns True when the caller's source changed (it skips
        the backoff sleep — a healthy alternative needs no cooldown). If
        a concurrent window already rotated away from ``from_peer``,
        that counts: the caller retries against the new source."""
        with self._peer_lock:
            current = self.peer
        if current != from_peer and current not in exclude:
            return True
        if len(self._peers) > 1:
            i = self._peers.index(current)
            for step in range(1, len(self._peers)):
                cand = self._peers[(i + step) % len(self._peers)]
                if cand != from_peer and cand not in exclude \
                        and self._health.allow(cand):
                    self._set_peer(cand)
                    return True
        return False

    def _add_fetched(self, n: int) -> None:
        if n:
            with self._count_lock:
                self.bytes_fetched += n
            # the delivery-rate counter the adaptive tuner (and anyone
            # watching /debug/telemetry) reads as a sliding-window rate
            metrics.HUB.inc("pull_bytes_total", n)

    # -- Store duck-type ------------------------------------------------
    def size(self, key: str) -> int:  # noqa: ARG002 — single-object reader
        return self._size

    def pread(self, key: str, length: int, offset: int) -> bytes:
        out = np.empty(length, dtype=np.uint8)
        got = self.pread_into(key, out, offset)
        return out[:got].tobytes()

    def pread_into(self, key: str, out, offset: int = 0) -> int:  # noqa: ARG002
        view = memoryview(out).cast("B")
        length = view.nbytes
        if length == 0:
            return 0
        if offset < 0 or offset + length > self._size:
            raise IOError(f"window [{offset}, {offset + length}) outside "
                          f"object of {self._size} bytes")
        if not trace.active():
            # span() args are evaluated eagerly — guard so the fully
            # disabled (DEMODEL_OBS=0) hot path pays neither the attrs
            # dict nor the _snapshot() lock acquire per window
            return self._pread_into_traced(view, length, offset,
                                           trace.NOOP)
        with trace.span("window-read", key=self.remote_key, offset=offset,
                        length=length, peer=self._snapshot()[0]) as sp:
            return self._pread_into_traced(view, length, offset, sp)

    def _pread_into_traced(self, view, length: int, offset: int,
                           sp) -> int:
        got = 0
        attempt = 0
        start = self._policy.clock()
        cannot_serve: set = set()  # peers that 404'd/range-refused THIS key
        while True:
            peer, native_host, native_port = self._snapshot()
            try:
                while got < length:
                    remaining = length - got
                    sub = view[got:]
                    if native_host and remaining >= _NATIVE_MIN_BYTES:
                        n = self._window_native(sub, offset + got, remaining,
                                                peer, native_host,
                                                native_port)
                    else:
                        n = self._window_requests(sub, offset + got,
                                                  remaining, peer)
                    self._add_fetched(n)
                    got += n
            except WindowAbort as e:
                # e.got bytes are already in the buffer AND already moved
                # over the wire — count them, keep them, never re-fetch
                self._add_fetched(e.got)
                got += e.got
                if retryable(e.cause):
                    # wire-shaped failure: health event + backoff budget
                    self._health.record_failure(peer)
                    attempt += 1
                    delay = self._policy.should_retry(attempt, start,
                                                      e.cause)
                    if delay is None:
                        raise IOError(
                            f"window [{offset}, +{length}) of "
                            f"{self.remote_key} failed at +{got} after "
                            f"{attempt} attempt(s): {e.cause}") from e.cause
                    count_retry(peer, delay)
                    switched = self._fail_over(peer, exclude=cannot_serve)
                    sp.event("retry", attempt=attempt, peer=peer,
                             resume_at=got,
                             error=f"{type(e.cause).__name__}: {e.cause}")
                    if switched:
                        sp.event("failover", from_peer=peer,
                                 to_peer=self._snapshot()[0],
                                 resume_at=got)
                    log.warning(
                        "window [%d, +%d) of %s died at +%d on %s (%s); "
                        "resuming at the exact offset via %s "
                        "(attempt %d/%d)",
                        offset, length, self.remote_key, got, peer,
                        e.cause, self._snapshot()[0], attempt + 1,
                        self._policy.max_attempts)
                    if not switched:
                        self._policy.sleep(delay)
                elif peer_cannot_serve(e.cause):
                    # content-shaped refusal (missing blob, range-blind
                    # peer): NOT a health event and a same-peer retry is
                    # a deterministic re-failure — rotate once per such
                    # peer, give up when no untried peer remains. The
                    # rotation deliberately includes partially-warm peers
                    cannot_serve.add(peer)
                    if (self._policy.deadline_left(start) <= 0
                            or not self._fail_over(peer,
                                                   exclude=cannot_serve)):
                        raise IOError(
                            f"window [{offset}, +{length}) of "
                            f"{self.remote_key}: no peer in the rotation "
                            f"can serve it ({e.cause})") from e.cause
                    sp.event("failover", from_peer=peer,
                             to_peer=self._snapshot()[0],
                             reason="cannot-serve", resume_at=got)
                    log.warning(
                        "peer %s cannot serve %s (%s); failing the window "
                        "over to %s", peer, self.remote_key, e.cause,
                        self._snapshot()[0])
                else:
                    raise IOError(
                        f"window [{offset}, +{length}) of "
                        f"{self.remote_key} failed at +{got}: "
                        f"{e.cause}") from e.cause
            else:
                self._health.record_success(peer)
                return length

    # -- transports -----------------------------------------------------
    def _window_native(self, view: memoryview, offset: int, length: int,
                       peer: str, native_host: str,
                       native_port: int) -> int:
        from demodel_tpu import native

        arr = np.frombuffer(view, dtype=np.uint8)
        errbuf = ctypes.create_string_buffer(512)
        n = native.lib().dm_peer_fetch_window(
            native_host.encode(), native_port,
            self.path.encode(),
            offset, length, self._size, self.streams,
            arr.ctypes.data_as(ctypes.c_void_p), errbuf, 512)
        if n != length:
            log.warning("native window fetch [%d,+%d) of %s failed (%s); "
                        "using requests", offset, length, self.remote_key,
                        errbuf.value.decode(errors="replace"))
            return self._window_requests(view, offset, length, peer)
        return int(n)

    def _window_requests(self, view: memoryview, offset: int,
                         length: int, peer: str) -> int:
        """One Range attempt against ``peer`` (an explicit snapshot — a
        concurrent failover must not swap the target mid-attempt). Bytes
        land in ``view`` as they arrive; any failure raises
        :class:`WindowAbort` carrying how many did, so the recovery loop
        in :meth:`pread_into` resumes — not restarts — the window."""
        s = getattr(self._tls, "session", None) or self._session
        if s is None:
            s = self._tls.session = requests.Session()
        got = 0
        try:
            # the ambient window-read span's traceparent rides the raw
            # streaming GET too (this path bypasses request_with_retry —
            # resume semantics live in pread_into)
            headers = trace.inject_headers(
                {"Range": f"bytes={offset}-{offset + length - 1}"})
            r = s.get(f"{peer}{self.path}", headers=headers,
                      stream=True, timeout=self.timeout)
            try:
                r.raise_for_status()
                if r.status_code != 206 and not (
                        r.status_code == 200 and offset == 0
                        and length == self._size):
                    raise RangeIgnored(
                        f"peer ignored Range (status {r.status_code}) "
                        f"for {self.remote_key}")
                for chunk in r.iter_content(1 << 20):
                    if not chunk:
                        continue
                    take = min(len(chunk), length - got)
                    view[got:got + take] = chunk[:take]
                    got += take
                    if got >= length:
                        break
            finally:
                r.close()
        except (requests.RequestException, WireError, OSError) as e:
            raise WindowAbort(got, e) from e
        if got != length:
            raise WindowAbort(got, TruncatedBody(
                f"short peer window read: {got} != {length} "
                f"for {self.remote_key}"))
        return got


def fetch_manifest(peers: list[str], model: str, source: str = "hf",
                   timeout: float = 30.0,
                   health: PeerHealth | None = None,
                   policy: RetryPolicy | None = None) -> tuple[str, dict]:
    """Locate and fetch the model-manifest record on a warm peer. Returns
    ``(peer_base_url, manifest_dict)``. The record is what the pull path
    persisted (`delivery._persist_manifest`), so ``files`` carries names,
    store keys, sizes, and digests — everything needed to place the model
    without any upstream registry round-trip.

    Breaker-aware: peers whose circuit breaker is open are skipped until
    their half-open probe succeeds (a dead peer must not cost discovery a
    full connect timeout); each attempted peer rides the retry policy."""
    mkey = manifest_key(source, model)
    health = health if health is not None else PeerHealth.shared()
    policy = policy if policy is not None else RetryPolicy()
    s = requests.Session()
    with trace.span("manifest-discovery", model=model, source=source,
                    peers=len(peers)):
        return _fetch_manifest(peers, mkey, model, source, timeout,
                               health, policy, s)


def _fetch_manifest(peers, mkey, model, source, timeout, health, policy,
                    s) -> tuple[str, dict]:
    last_err: Exception | None = None
    candidates = [p.rstrip("/") for p in peers]
    # read-only admission filter (burns no probe slots); the claiming
    # allow() happens right before each dial below
    admitted = [p for p in candidates if health.admissible(p)]
    if len(admitted) < len(candidates):
        log.info("manifest discovery skipping %d breaker-open peer(s)",
                 len(candidates) - len(admitted))
    last_resort = not admitted
    if last_resort:
        # every breaker refuses: a last-resort sweep beats turning a
        # brown-out into an outage
        admitted = candidates
    for peer in admitted:
        if not last_resort and not health.allow(peer):
            continue  # raced shut, or another caller owns the probe
        try:
            r = request_with_retry(
                s, "GET", f"{peer}/peer/object/{mkey}",
                policy=policy, health=health, peer=peer,
                ok_statuses=(404,), timeout=timeout,
                what=f"manifest {source}/{model} from {peer}")
            if r.status_code == 404:
                continue
            return peer, r.json()
        except (requests.RequestException, OSError, ValueError) as e:
            last_err = e
            log.warning("peer %s manifest for %s failed: %s", peer, model, e)
    raise IOError(f"no peer holds a manifest for {source}/{model}"
                  + (f" (last error: {last_err})" if last_err else ""))


def _peer_alive(peer: str, timeout: float = 3.0) -> bool:
    """Short-deadline liveness probe (``/healthz`` on the native proxy).
    Only gates which peers join the striping rotation — the manifest
    peer is already proven by the manifest fetch itself. Single attempt
    (a retry would defeat the short deadline); the outcome feeds the
    shared breaker registry."""
    try:
        request_with_retry(
            requests, "GET", f"{peer}/healthz",
            policy=RetryPolicy(max_attempts=1, deadline=timeout),
            health=PeerHealth.shared(), peer=peer.rstrip("/"),
            timeout=timeout, what=f"liveness {peer}")
        return True
    except (requests.RequestException, OSError):
        return False


def _alive_peers(peers: list, timeout: float = 3.0) -> list:
    """Probe every candidate peer CONCURRENTLY under one shared deadline.

    The striping rotation used to probe candidates one at a time: K
    stale peer URLs on the pull critical path cost K × timeout before
    the first byte moved. Here each probe rides ``asyncio.to_thread``
    and the whole rotation build is bounded by ~timeout: stragglers are
    cancelled at the deadline (on every exit path — the
    ``orphaned-async-task`` discipline) and treated as dead. Their probe
    threads may run on to their socket timeout; ``asyncio.run`` joins
    them at loop shutdown, so nothing leaks — worst case is ~2×timeout
    total, independent of peer count.
    """
    if not peers:
        return []
    import asyncio

    try:
        asyncio.get_running_loop()
    except RuntimeError:
        pass  # no loop in this thread — the asyncio path below owns one
    else:
        # asyncio.run would raise "cannot be called from a running event
        # loop": a serving node's async handler pulling a model lands
        # exactly here — probe on a thread pool instead
        return _alive_peers_threaded(peers, timeout)

    async def _probe_all() -> list:
        tasks = {
            p: asyncio.create_task(asyncio.to_thread(_peer_alive, p, timeout))
            for p in peers
        }
        done: set = set()
        try:
            done, _pending = await asyncio.wait(
                set(tasks.values()), timeout=timeout + 0.5)
        finally:
            for t in tasks.values():
                t.cancel()  # no-op on done tasks; orphans none on errors
        return [p for p, t in tasks.items()
                if t in done and not t.cancelled()
                and t.exception() is None and t.result()]

    return asyncio.run(_probe_all())


def _alive_peers_threaded(peers: list, timeout: float = 3.0) -> list:
    """`_alive_peers` for callers whose thread already runs an event loop:
    same shape — concurrent probes, one shared deadline — on a thread
    pool. Stragglers past the deadline are treated dead; their probe
    threads run on to the socket timeout and exit on their own
    (``shutdown(wait=False)`` — joining them here would hold the caller
    for the full socket timeout, the exact stall this function exists to
    avoid; worst case is ~2×timeout of background lingering, same bound
    as the asyncio path's loop-shutdown join)."""
    from concurrent.futures import ThreadPoolExecutor, wait

    ex = ThreadPoolExecutor(max_workers=min(32, len(peers)),
                            thread_name_prefix="peer-probe")
    try:
        futs = {p: ex.submit(_peer_alive, p, timeout) for p in peers}
        done, _pending = wait(set(futs.values()), timeout=timeout + 0.5)
        return [p for p, f in futs.items()
                if f in done and not f.cancelled()
                and f.exception() is None and f.result()]
    finally:
        ex.shutdown(wait=False, cancel_futures=True)


def _responsive_peers(peers: list, timeout: float = 3.0) -> list:
    """The striping-rotation membership check, gossip-first: peers whose
    background index refresh (:class:`~demodel_tpu.parallel.peer
    .PeerGossip`) answered recently join with ZERO wire traffic on the
    pull critical path, fresh-failed peers drop out, and only peers the
    gossip has never heard from fall back to the one-shot concurrent
    probe round (the cold-start shape). Every pull also enrolls its
    peers for background refresh, so pull #2 onward probes nothing."""
    if not peers:
        return []
    from demodel_tpu.parallel.peer import PeerGossip

    gossip = PeerGossip.shared()
    gossip.track(peers)
    alive, dead, unknown = gossip.split(peers)
    if dead:
        log.info("striping rotation drops %d gossip-dead peer(s)",
                 len(dead))
    return alive + (_alive_peers(unknown, timeout) if unknown else [])


def _reader_and_index(f: dict, peer_order: list[str], streams):
    """Open ``f`` on the first peer that can serve its safetensors index
    (header reads fail over peer-by-peer here; window reads during
    delivery recover inside the reader — resume-at-offset plus failover
    to the rest of the rotation)."""
    from demodel_tpu.formats import safetensors as st

    last_err: Exception | None = None
    for i, source_peer in enumerate(peer_order):
        reader = PeerBlobReader(
            source_peer, f["key"], int(f["size"]), streams=streams,
            failover=peer_order[i + 1:] + peer_order[:i])
        try:
            with trace.span("index-read", file=f["name"],
                            peer=source_peer):
                index = st.read_index_from(
                    lambda off, ln: reader.pread(f["key"], ln, off),
                    total_size=reader.size(f["key"]))
            return reader, index
        except (OSError, ValueError) as e:
            # ValueError: a corrupted/truncated safetensors header parses
            # as junk — same failover as a transport error, the next peer
            # holds a good copy
            last_err = e
            log.warning("index of %s from %s failed (%s); trying next "
                        "peer", f["name"], source_peer, e)
    raise IOError(f"no peer could serve {f['name']}") from last_err


# --------------------------------------------------------------- swarm fetch
#
# Pod-scale cold pull: N hosts pulling the same manifest partition every
# file's fixed chunk grid over a consistent-hash ring (disjoint origin
# chunk sets), fetch ONLY their owned chunks from origin, and cross-fill
# the rest from each other as possession advertisements land — aggregate
# origin traffic ≈ 1× the manifest, origin-bound wall-clock ≈ size/N.
# The per-chunk transport is the existing window machinery
# (PeerBlobReader.pread_into: resume-at-offset, breaker-gated failover),
# so WindowAbort semantics hold inside every chunk.


def _swarm_chunk_id(key: str, index: int) -> str:
    return f"{key}:{index}"


def _swarm_origin_read(reader: PeerBlobReader, key: str, offset: int,
                       length: int) -> bytes:
    """THE origin transport of the swarm plane: one owned (or re-owned)
    chunk off the origin/warm-peer rotation. Every origin byte a swarm
    pull moves goes through here — the ``swarm-owner-only-origin``
    analyzer rule keeps callers inside :class:`SwarmScheduler`, where the
    ownership decision lives, so no code path can quietly degrade the
    aggregate-origin-bytes ≈ 1× contract back into N× origin pulls."""
    buf = bytearray(length)
    with trace.span("chunk-origin", key=key, offset=offset, bytes=length):
        reader.pread_into(key, buf, offset)
    metrics.HUB.inc("swarm_origin_bytes_total", length)
    return bytes(buf)


class SwarmScheduler:
    """Chunk-level swarm fetch for one pull on one host.

    ``participants``: ``{host_id: base_url}`` of every host in the swarm
    (including this one — ``self_id`` selects which). All hosts build the
    same :class:`HashRing` over the sorted host ids, so chunk ownership
    needs no coordination traffic at all.

    Three background roles run between :meth:`start` and :meth:`close`:

    - the **origin pump** fetches this host's owned chunks from the
      origin rotation, rarest-first-ish (fewest known advertisers, hash
      tie-break — hosts' request orders decorrelate, so the swarm's
      earliest cross-fills spread over the whole grid);
    - the **gossip poller** refreshes every sibling's possession bitmap
      (``/swarm/{pull}/{host}/chunks``) and declares siblings dead after
      consecutive poll failures;
    - **fill workers** pull advertised non-owned chunks from whichever
      sibling has them (``chunk-peer-fill``), landing them on the local
      :class:`ChunkBoard` — which the restore server re-serves, so a
      chunk crosses origin once and then propagates peer-to-peer.

    Death handling is succession, not re-pull: a dead owner's chunk is
    re-owned by the next live host on its ring arc; only that successor
    goes back to origin (counted in ``swarm_chunks_refetched_total``),
    everyone else cross-fills from the successor.
    """

    def __init__(self, pull_id: str, self_id: str,
                 participants: dict[str, str],
                 chunk_bytes: int | None = None,
                 health: PeerHealth | None = None,
                 policy: RetryPolicy | None = None):
        if self_id not in participants:
            raise ValueError(f"self_id {self_id!r} not in participants")
        self.pull_id = pull_id
        self.self_id = self_id
        self.participants = dict(participants)
        self.chunk_bytes = chunk_bytes or default_chunk_bytes()
        self.ring = HashRing(sorted(participants))
        self.board = ChunkBoard(pull_id, self_id)
        self._health = health if health is not None else PeerHealth.shared()
        self._policy = policy if policy is not None else RetryPolicy()
        #: per-owner wait before a chunk succeeds to the next ring host.
        #: Sized for a live-but-busy owner, not a dead one (death is
        #: detected in ~3 gossip ticks): on a big manifest the LAST
        #: chunk of an owner's rarest-first queue legitimately takes its
        #: whole owned share's origin time to appear, so a small value
        #: here re-fetches healthy hosts' chunks and erodes the 1×
        #: origin contract
        self._fill_timeout = swarm_placement.default_fill_timeout()
        self._gossip_s = env_int(
            "DEMODEL_SWARM_GOSSIP_MS", 500, minimum=10) / 1000.0
        self._fill_streams = env_int(
            "DEMODEL_SWARM_FILL_STREAMS", 4, minimum=1)
        #: concurrent origin CONNECTIONS per host (the pump + any
        #: ensure-inline re-own fetch share it): the disjoint-chunk-set
        #: contract bounds each host's origin LINK use, so the default
        #: is one stream — multi-stream parallelism belongs inside a
        #: window (DEMODEL_PEER_STREAMS), not across origin chunks
        self._origin_sem = threading.Semaphore(
            swarm_placement.default_origin_streams())
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        #: file key → (size, n_chunks, origin PeerBlobReader)
        self._files: dict[str, tuple[int, int, PeerBlobReader]] = {}
        self._primary: dict[tuple[str, int], str] = {}
        self._owned: list[tuple[str, int]] = []
        self._inflight: set[tuple[str, int]] = set()
        self._peer_have: dict[str, dict[str, set[int]]] = {}
        #: gossiped done-sets (have ∪ reaped) per sibling — the reap
        #: gate; _peer_have stays strictly what a sibling can SERVE
        self._peer_done: dict[str, dict[str, set[int]]] = {}
        self._peer_ver: dict[str, int] = {}
        self._poll_fails: dict[str, int] = {}
        self._dead: set[str] = set()
        self._peer_bytes: dict[str, int] = {}   # file key → peer-fill bytes
        self._spread: dict[tuple[str, int], int] = {}  # rarest tie-break
        self.chunks_refetched = 0
        #: offsets of in-flight read_into calls per file — the reaper
        #: never frees below an active read's start
        self._active_reads: dict[str, list[int]] = {}
        #: per-file local consumption watermark (highest byte offset a
        #: read_into has fully passed) — the reaper only frees chunks the
        #: local delivery is already beyond, so a long pull's board stops
        #: retaining the whole file set until close()
        self._consumed_upto: dict[str, int] = {}
        self._reap = swarm_placement.reap_enabled()
        self._reap_s = max(2 * self._gossip_s, 0.5)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._tls = threading.local()
        swarm_placement.register_board(self.board)

    # -- planning --------------------------------------------------------
    def add_file(self, key: str, size: int,
                 origin_reader: PeerBlobReader) -> None:
        """Register one manifest file's chunk grid (call for every
        weight file BEFORE start — ownership is assigned over the WHOLE
        grid at once so the capacity bound balances across files)."""
        if self._threads:
            raise RuntimeError("add_file after start(): the ownership "
                               "assignment is already fixed")
        n = chunk_count(size, self.chunk_bytes)
        with self._lock:
            self._files[key] = (int(size), n, origin_reader)
            self._peer_bytes.setdefault(key, 0)
        self.board.add_file(key, n)

    def _plan(self) -> None:
        """The ownership decision for the whole grid: ring succession
        for agreement + death recovery, bounded loads for balance (the
        swarm's wall-clock is the LARGEST owned share's origin time)."""
        with self._lock:
            grid = [(k, i) for k, (_s, n, _r) in sorted(self._files.items())
                    for i in range(n)]
        with trace.span("swarm-schedule", chunks=len(grid),
                        files=len(self._files),
                        hosts=len(self.participants)) as sp:
            assigned = bounded_assign(
                self.ring, [_swarm_chunk_id(k, i) for k, i in grid])
            # demodel: allow(atomic-snapshot) — _plan runs from start()
            # BEFORE any pump thread exists and add_file refuses
            # post-start registration, so the grid cannot change between
            # the two holds (single-threaded by lifecycle contract)
            with self._lock:
                self._primary = {
                    (k, i): assigned[_swarm_chunk_id(k, i)]
                    for k, i in grid}
                self._owned = [c for c, owner in self._primary.items()
                               if owner == self.self_id]
                owned_n = len(self._owned)
            sp.set_attr("owned", owned_n)

    def start(self) -> "SwarmScheduler":
        if self._threads:
            return self
        self._plan()
        self._threads.append(threading.Thread(
            target=self._pump_origin, name="swarm-pump", daemon=True))
        if self._reap:
            self._threads.append(threading.Thread(
                target=self._pump_reap, name="swarm-reap", daemon=True))
        if len(self.participants) > 1:
            self._threads.append(threading.Thread(
                target=self._pump_gossip, name="swarm-gossip", daemon=True))
            for i in range(self._fill_streams):
                self._threads.append(threading.Thread(
                    target=self._pump_fill, name=f"swarm-fill-{i}",
                    daemon=True))
        for t in self._threads:
            t.start()
        return self

    def close(self) -> None:
        """Stop the pumps, free the board, unregister the serve surface.
        The caller decides WHEN: closing before every sibling has the
        bytes pushes the swarm's stragglers back to origin."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=30)
        self._threads.clear()
        swarm_placement.unregister_board(self.board)
        self.board.clear()

    # -- read surface ----------------------------------------------------
    def peer_bytes_for(self, key: str) -> int:
        with self._lock:
            return self._peer_bytes.get(key, 0)

    def read_into(self, key: str, view: memoryview, offset: int) -> int:
        """Copy ``[offset, offset+len(view))`` of ``key`` out of the
        board, blocking per covering chunk until the swarm lands it."""
        with self._lock:
            size, _n, _r = self._files[key]
        length = view.nbytes
        if offset < 0 or offset + length > size:
            raise IOError(f"swarm window [{offset}, {offset + length}) "
                          f"outside {key} of {size} bytes")
        # register as an in-flight read: the reaper's safe-to-free floor
        # is min(active read starts, completed high-water) — prefetch
        # workers complete out of order as the norm, and a reap under a
        # still-running lower-offset read would force an origin re-fetch
        with self._lock:
            self._active_reads.setdefault(key, []).append(offset)
        try:
            pos = 0
            while pos < length:
                idx = (offset + pos) // self.chunk_bytes
                c_off, c_len = chunk_span(size, self.chunk_bytes, idx)
                data = self.ensure(key, idx)
                lo = offset + pos - c_off
                take = min(c_len - lo, length - pos)
                view[pos:pos + take] = data[lo:lo + take]
                pos += take
        finally:
            with self._lock:
                self._active_reads[key].remove(offset)
        # completed-read high-water: delivery walks files in (mostly)
        # ascending offset order, so chunks wholly below it — and below
        # every still-active read — are done locally; a rare later
        # re-read of a reaped chunk degrades to one counted re-fetch,
        # never a wrong byte
        with self._lock:
            if offset + length > self._consumed_upto.get(key, 0):
                self._consumed_upto[key] = offset + length
        return length

    def fetch_all(self) -> None:
        """Block until EVERY chunk of every registered file is on the
        board — swarm participation for a host that isn't also delivering
        to HBM (bench hosts, warm standbys)."""
        with self._lock:
            grid = [(k, i) for k, (_s, n, _r) in sorted(self._files.items())
                    for i in range(n)]
        for key, idx in grid:
            self.ensure(key, idx)

    # -- chunk acquisition ----------------------------------------------
    def ensure(self, key: str, index: int) -> bytes:
        """The ownership decision: return chunk bytes, sourcing them per
        the assignment — owned → origin; non-owned → wait for the
        owner's advertisement and cross-fill; owner dead/stuck →
        succession along the raw ring order, where only the next live
        host re-sources from origin."""
        chunk_id = _swarm_chunk_id(key, index)
        with self._lock:
            primary = self._primary.get((key, index))
        if primary is None:
            raise RuntimeError("ensure() before start(): no ownership "
                               "assignment yet")
        owners = [primary] + [
            o for o in self.ring.owners(chunk_id, len(self.participants))
            if o != primary]
        waited_since: dict[str, float] = {}
        while not self._stop.is_set():
            data = self.board.get(key, index)
            if data is not None:
                return data
            if self.board.reaped(key, index):
                # a local re-read below the consumption watermark wants a
                # chunk the reaper freed: re-land it from origin OURSELVES.
                # The chunk already crossed the wire once, and the live
                # siblings have likely reaped it too (reaping requires
                # every one of them to have advertised it) — the
                # owner-wait path below would stall out the fill timeout
                # and falsely condemn a healthy owner that simply cannot
                # serve a chunk it also freed.
                self.board.unreap(key, index)
                metrics.HUB.inc("swarm_chunks_unreaped_total")
                self._fetch_origin(key, index, reowned=False)
                continue
            live = [o for o in owners if o not in self._snapshot_dead()]
            target = live[0] if live else self.self_id
            if target == self.self_id:
                self._fetch_origin(key, index,
                                   reowned=(owners[0] != self.self_id))
                continue
            # a sibling owns it: grab it the moment an advertiser shows
            # (ANY advertiser — cross-filled copies count), else wait
            adv = self._advertisers(key, index)
            if adv:
                if self._fetch_peer(key, index, adv):
                    continue
            now = time.monotonic()
            waited_since.setdefault(target, now)
            if now - waited_since[target] > self._fill_timeout:
                # the live owner never produced the chunk (wedged, not
                # dead-dialed): succession treats it as gone
                with self._lock:
                    self._dead.add(target)
                    self._cv.notify_all()
                log.warning(
                    "swarm owner %s never advertised chunk %s/%d within "
                    "%.0fs; treating it as dead (succession)", target,
                    key, index, self._fill_timeout)
                # its other orphans join our pump where we're successor
                self._take_over_orphans()
                continue
            with self._cv:
                self._cv.wait(timeout=min(0.2, self._gossip_s))
        raise IOError(f"swarm pull {self.pull_id} closed while waiting "
                      f"for chunk {key}/{index}")

    def _snapshot_dead(self) -> set[str]:
        with self._lock:
            return set(self._dead)

    def _advertisers(self, key: str, index: int) -> list[str]:
        with self._lock:
            return [h for h, files in self._peer_have.items()
                    if h not in self._dead and index in files.get(key, ())]

    def _claim(self, key: str, index: int) -> bool:
        with self._lock:
            if (key, index) in self._inflight \
                    or self.board.done(key, index):
                return False
            self._inflight.add((key, index))
            return True

    def _release(self, key: str, index: int) -> None:
        with self._cv:
            self._inflight.discard((key, index))
            self._cv.notify_all()

    def _fetch_origin(self, key: str, index: int,
                      reowned: bool = False) -> None:
        if not self._claim(key, index):
            # someone else is on it — wait for their outcome
            with self._cv:
                self._cv.wait(timeout=0.2)
            return
        try:
            with self._lock:
                size, _n, reader = self._files[key]
            off, ln = chunk_span(size, self.chunk_bytes, index)
            with self._origin_sem:
                data = _swarm_origin_read(reader, key, off, ln)
            if reowned:
                with self._lock:
                    self.chunks_refetched += 1
                metrics.HUB.inc("swarm_chunks_refetched_total")
                log.info("swarm re-owned chunk %s/%d from origin "
                         "(owner dead)", key, index)
            self.board.put(key, index, data)
        finally:
            self._release(key, index)

    def _session(self) -> requests.Session:
        s = getattr(self._tls, "session", None)
        if s is None:
            s = self._tls.session = requests.Session()
        return s

    def _fetch_peer(self, key: str, index: int,
                    advertisers: list[str]) -> bool:
        """One cross-fill attempt off the best advertiser (ring owner
        first). Returns True when the chunk landed (or someone else's
        fetch is in flight — the caller re-checks the board)."""
        if not self._claim(key, index):
            return True
        chunk_id = _swarm_chunk_id(key, index)
        order = [o for o in self.ring.owners(chunk_id,
                                             len(self.participants))
                 if o in advertisers] or advertisers
        try:
            with self._lock:
                size, _n, _r = self._files[key]
            _off, ln = chunk_span(size, self.chunk_bytes, index)
            for host in order:
                url = self.participants[host]
                try:
                    with trace.span("chunk-peer-fill", key=key,
                                    index=index, peer=host, bytes=ln):
                        r = request_with_retry(
                            self._session(), "GET",
                            f"{url}/swarm/{self.pull_id}/{host}"
                            f"/chunk/{key}/{index}",
                            policy=RetryPolicy(max_attempts=2,
                                               deadline=30.0),
                            health=self._health, peer=url.rstrip("/"),
                            timeout=30.0,
                            what=f"swarm chunk {key}/{index} from {host}")
                    if len(r.content) != ln:
                        raise TruncatedBody(
                            f"swarm chunk {key}/{index}: "
                            f"{len(r.content)} != {ln}")
                    metrics.HUB.inc("swarm_peer_bytes_total", ln)
                    with self._lock:
                        self._peer_bytes[key] = \
                            self._peer_bytes.get(key, 0) + ln
                    self.board.put(key, index, r.content)
                    return True
                except (requests.RequestException, WireError, OSError) as e:
                    log.warning("swarm fill of %s/%d from %s failed: %s",
                                key, index, host, e)
                    self._poll_failed(host)
            return False
        finally:
            self._release(key, index)

    # -- background pumps ------------------------------------------------
    def _pump_origin(self) -> None:
        """Owned chunks off origin, rarest-first-ish: among the remaining
        owned set, always the chunk the fewest siblings advertise (hash
        tie-break decorrelates hosts) — the swarm's rarest pieces cross
        origin earliest, classic BitTorrent scheduling. Runs until
        close(): succession can grow the owned set at any time
        (_take_over_orphans), so an idle pump parks on the cv instead of
        exiting."""
        while not self._stop.is_set():
            with self._lock:
                remaining = [c for c in self._owned
                             if c not in self._inflight
                             and not self.board.done(*c)]
                # one possession snapshot per pick, not one lock-held
                # _advertisers() scan per candidate: a 13 GB manifest is
                # ~1700 owned chunks on a solo host and re-scoring the
                # whole remainder under the scheduler lock every fetch
                # contends with ensure()/fill workers for the pull's
                # entire duration
                peer_have = {h: files
                             for h, files in self._peer_have.items()
                             if h not in self._dead}
            if not remaining:
                with self._cv:
                    self._cv.wait(timeout=0.5)
                continue

            def rarity(c: tuple[str, int]) -> tuple[int, int]:
                sk = self._spread.get(c)
                if sk is None:
                    sk = self._spread[c] = swarm_placement.spread_key(
                        _swarm_chunk_id(*c))
                n = sum(1 for files in peer_have.values()
                        if c[1] in files.get(c[0], ()))
                return (n, sk)

            key, index = min(remaining, key=rarity)
            with self._lock:
                reowned = self._primary.get((key, index)) != self.self_id
            try:
                # demodel: allow(atomic-snapshot) — _primary is
                # write-once at plan time (pre-start), so the reowned
                # verdict cannot go stale between the holds; the fetch
                # itself re-claims under the lock before any work
                self._fetch_origin(key, index, reowned=reowned)
            except IOError as e:
                log.warning("swarm origin fetch of %s/%d failed: %s "
                            "(will retry / re-ensure on demand)",
                            key, index, e)
                with self._cv:
                    self._cv.wait(timeout=0.5)

    def _pump_gossip(self) -> None:
        # dead hosts stay in the poll rotation: death is a ROUTING
        # verdict (stop waiting on it, succession takes its chunks), not
        # a ban — a wedged-then-recovered or restarted sibling re-enters
        # on its first successful poll (merge_summary resurrects it)
        siblings = [h for h in self.participants if h != self.self_id]
        while not self._stop.is_set():
            for host in siblings:
                if self._stop.is_set():
                    return
                self._poll_one(host)
            self._stop.wait(self._gossip_s)

    def _poll_one(self, host: str) -> None:
        # deliberately span-free and single-attempt (a raw session.get,
        # not request_with_retry): a background poll failing against a
        # dead sibling is ROUTINE — it must not become an error-status
        # root span that trips the flight recorder's incident dump, and
        # the next poll tick IS the retry
        url = self.participants[host]
        try:
            r = self._session().get(
                f"{url}/swarm/{self.pull_id}/{host}/chunks", timeout=5.0)
            r.raise_for_status()
            self.merge_summary(host, r.json())
        except (requests.RequestException, OSError, ValueError,
                TypeError):
            self._poll_failed(host)

    def merge_summary(self, host: str, summary: dict) -> None:
        """Versioned merge of one sibling's possession bitmap (also fed
        by tests/bench driving in-process boards directly)."""
        if not isinstance(summary, dict):
            return
        try:
            version = int(summary.get("v", 0))
            files = summary.get("files", {})
            have = {
                str(k): bitmap_indices(str(spec.get("have", "")),
                                       int(spec.get("n", 0)))
                for k, spec in files.items() if isinstance(spec, dict)
            }
            # done ⊇ have: landed-at-least-once (reaped included) — the
            # reap gate. A summary without it (older sibling) degrades
            # to have, which merely delays our reap, never corrupts
            done = {
                str(k): bitmap_indices(str(spec.get("done",
                                                    spec.get("have", ""))),
                                       int(spec.get("n", 0)))
                for k, spec in files.items() if isinstance(spec, dict)
            }
        except (TypeError, ValueError, AttributeError):
            return  # junk gossip degrades to nothing, never a crash
        with self._cv:
            # a DEAD host's successful poll always wins: a restarted
            # sibling's board restarts its version counter near zero, so
            # holding it to the old high-water mark would veto the very
            # resurrection _pump_gossip promises
            if host not in self._dead \
                    and version < self._peer_ver.get(host, -1):
                return  # stale reordering
            self._peer_ver[host] = version
            self._peer_have[host] = have
            self._peer_done[host] = done
            self._poll_fails[host] = 0
            if host in self._dead:
                # resurrection: chunks already taken over stay ours
                # (board dedupe makes the overlap at most one extra
                # origin chunk each), but the host serves cross-fills
                # and keeps its not-yet-orphaned chunks again
                self._dead.discard(host)
                log.info("swarm sibling %s resurrected (gossip poll "
                         "succeeded)", host)
            self._cv.notify_all()

    def _poll_failed(self, host: str) -> None:
        died = False
        with self._cv:
            fails = self._poll_fails.get(host, 0) + 1
            self._poll_fails[host] = fails
            if fails >= 3 and host not in self._dead:
                self._dead.add(host)
                died = True
                log.warning("swarm sibling %s declared dead after %d "
                            "straight failures; its chunks re-own via "
                            "ring succession", host, fails)
            self._cv.notify_all()
        if died:
            self._take_over_orphans()

    def _take_over_orphans(self) -> None:
        """Proactive succession: chunks whose primary is dead and whose
        first LIVE ring successor is this host join the origin pump now
        — a waiting sibling cross-fills from us instead of timing out
        into its own origin fetch (which would double-move the bytes)."""
        with self._cv:
            dead = set(self._dead)
            mine = set(self._owned)
            takeover = []
            for (key, idx), primary in self._primary.items():
                if primary not in dead or (key, idx) in mine:
                    continue
                chunk_id = _swarm_chunk_id(key, idx)
                live = [o for o in self.ring.owners(
                            chunk_id, len(self.participants))
                        if o == self.self_id or o not in dead]
                if live and live[0] == self.self_id:
                    takeover.append((key, idx))
            if not takeover:
                return
            self._owned.extend(takeover)
            self._cv.notify_all()
        log.info("swarm succession: taking over %d orphaned chunk(s) "
                 "from dead sibling(s) %s", len(takeover), sorted(dead))

    def _pump_reap(self) -> None:
        """The chunk-board reaper (ROADMAP swarm item b): periodically
        frees chunks that (a) EVERY live sibling already advertises
        possessing — the possession data is already gossiped, so nobody
        will ask us for them — and (b) the local delivery has consumed
        past, so a long pull's board stops retaining the whole file set
        until close(). A solo board (no siblings) reaps on consumption
        alone: there is no swarm left to serve."""
        while not self._stop.is_set():
            self._stop.wait(self._reap_s)
            if self._stop.is_set():
                return
            for key, index in self._reap_candidates():
                freed = self.board.reap(key, index)
                if freed:
                    metrics.HUB.inc("swarm_chunks_reaped_total")
                    metrics.HUB.inc("swarm_bytes_reaped_total", freed)

    def _reap_candidates(self) -> list[tuple[str, int]]:
        with self._lock:
            live = [h for h in self.participants
                    if h != self.self_id and h not in self._dead]
            # gate on the gossiped DONE sets (have ∪ reaped): a sibling
            # that reaped first stops ADVERTISING a chunk, and gating on
            # its have-set would block everyone who consumes later from
            # ever reaping (the normal case in a skewed pod)
            peer_done = {h: self._peer_done.get(h, {}) for h in live}
            sizes = {k: s for k, (s, _n, _r) in self._files.items()}
            consumed = dict(self._consumed_upto)
            # an in-flight read at offset s may still need chunks ≥ s:
            # prefetch workers complete out of order as the NORM, so the
            # completed-read high-water alone would reap under a slower
            # low-offset job and force counted origin re-fetches
            floors = {k: min(starts) for k, starts
                      in self._active_reads.items() if starts}
        out = []
        for key, index in self.board.held():
            size = sizes.get(key)
            if size is None:
                continue
            c_off, c_len = chunk_span(size, self.chunk_bytes, index)
            safe_upto = min(consumed.get(key, 0),
                            floors.get(key, float("inf")))
            if c_off + c_len > safe_upto:
                continue  # local delivery may still need it
            if all(index in peer_done[h].get(key, ()) for h in live):
                out.append((key, index))
        return out

    def _pump_fill(self) -> None:
        """Cross-fill any advertised, non-local, non-owned chunk — the
        keep-the-pipe-full role; ensure() only ever waits for chunks the
        pumps haven't reached yet."""
        while not self._stop.is_set():
            target = None
            with self._lock:
                for host, files in self._peer_have.items():
                    if host in self._dead:
                        continue
                    for key, idxs in files.items():
                        if key not in self._files:
                            continue
                        for i in sorted(idxs):
                            if (key, i) not in self._inflight \
                                    and not self.board.done(key, i):
                                target = (key, i)
                                break
                        if target:
                            break
                    if target:
                        break
            if target is None:
                with self._cv:
                    self._cv.wait(timeout=self._gossip_s)
                continue
            # demodel: allow(atomic-snapshot) — the pick is ADVISORY:
            # _advertisers re-reads liveness and _fetch_peer's _claim
            # re-validates inflight/done under the lock before any
            # bytes move, so a stale pick costs one no-op loop, never
            # a wrong transfer
            adv = self._advertisers(*target)
            if adv:
                # demodel: allow(atomic-snapshot) — same advisory pick:
                # _claim re-validates under the lock before any bytes move
                self._fetch_peer(*target, adv)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "pull": self.pull_id, "host": self.self_id,
                "hosts": len(self.participants),
                "owned_chunks": len(self._owned),
                "chunks_refetched": self.chunks_refetched,
                "dead": sorted(self._dead),
                "peer_fill_bytes": sum(self._peer_bytes.values()),
            }
        out.update(self.board.stats())
        return out


class SwarmBlobReader:
    """Store-shaped reads served off a swarm scheduler's chunk board —
    what the delivery pipeline sees instead of a raw origin reader when
    a pull runs in swarm mode. ``bytes_fetched`` keeps the pod-delivery
    accounting honest: origin bytes (via the wrapped reader, headers
    included) + peer-fill bytes attributed to this file."""

    def __init__(self, scheduler: SwarmScheduler, remote_key: str,
                 size: int, origin_reader: PeerBlobReader):
        self.scheduler = scheduler
        self.remote_key = remote_key
        self._size = int(size)
        self._origin = origin_reader

    @property
    def bytes_fetched(self) -> int:
        return self._origin.bytes_fetched \
            + self.scheduler.peer_bytes_for(self.remote_key)

    def size(self, key: str) -> int:  # noqa: ARG002 — single-object reader
        return self._size

    def pread(self, key: str, length: int, offset: int) -> bytes:
        out = bytearray(length)
        self.pread_into(key, out, offset)
        return bytes(out)

    def pread_into(self, key: str, out, offset: int = 0) -> int:  # noqa: ARG002
        view = memoryview(out).cast("B")
        if view.nbytes == 0:
            return 0
        return self.scheduler.read_into(self.remote_key, view, offset)


class PipelineFailure(OSError):
    """A mid-pipeline delivery failure carrying the tensors that DID
    land before the error — the caller resumes from them instead of
    redoing every device transfer (VERDICT r4 weak #4: one flaky window
    at shard 14 of a 15-shard pull must not cost the whole pull)."""

    def __init__(self, cause: OSError, partial: "Placement"):
        super().__init__(str(cause))
        self.cause = cause
        self.partial = partial


def _deliver_jobs_pipelined(jobs, mesh, plan, cast_to=None,
                            prefetch_depth: int | None = None) -> Placement:
    """Single-process safetensors delivery with a tensor prefetch window
    spanning FILE boundaries: while tensor N's ``device_put`` is in
    flight, the next ``prefetch_depth`` tensors' byte windows download
    (multi-stream, native) — wall-clock ≈ max(network, host→HBM) instead
    of their sum, with no bubble between files. Only used when this
    process addresses the whole mesh (a pod host must fetch exactly its
    shard windows instead — prefetching whole tensors would defeat shard
    reads).

    ``jobs``: ``[(reader, key, name, spec)]`` in manifest order.
    """
    from concurrent.futures import ThreadPoolExecutor

    from demodel_tpu.formats.safetensors import _np_dtype
    from demodel_tpu.sink import tuner as tuner_mod
    from demodel_tpu.sink.hbm import place_tensor
    from demodel_tpu.sink.streaming import ByteBudget

    if prefetch_depth is None:
        # prefetch overlap needs either a SPARE core or a transfer that
        # leaves the core: on a single-CPU host with the CPU backend,
        # "device_put" is a memcpy on the same core and even one
        # background fetch thread contends (598 vs 238 MB/s at 1 GiB) —
        # default 0, fully synchronous. On a REAL TPU the host→device
        # transfer runs in the runtime off the GIL, so one fetch thread
        # overlaps it even on one core; multi-core keeps depth 2.
        import jax as _jax

        from demodel_tpu.utils.env import available_cpus

        if available_cpus() > 1:
            default_depth = 2
        elif _jax.default_backend() == "tpu":
            default_depth = 1
        else:
            default_depth = 0
        prefetch_depth = env_int(
            "DEMODEL_SINK_PREFETCH", default_depth, minimum=0)
    out = Placement(mesh_desc=f"{dict(mesh.shape)}")
    # landing buffers are charged to the SAME byte budget the streaming
    # sink enforces (DEMODEL_SINK_BUFFER_MB): before this, prefetch
    # workers could pin depth × tensor bytes of host RAM with no bound —
    # the accounting gap the hbm-budget analyzer rule flags
    budget = ByteBudget(env_int("DEMODEL_SINK_BUFFER_MB", 1024,
                                minimum=1) << 20)

    # FIFO admission tickets: budget grants MUST follow job order. The
    # main loop consumes futures in order, so if a later window could
    # win capacity freed for an earlier one, the three-way wait closes:
    # main blocks on the earlier future, whose worker blocks in acquire,
    # waiting for a release that only happens when main places the LATER
    # buffer. With tickets, the head job is the only one in acquire, and
    # everything it waits on is already in main's consume path.
    admission = {"next": 0, "dead": False}
    admit_cv = threading.Condition()

    # the closed loop: an AIMD controller reads the live windowed
    # telemetry (window-read p99, retry rate, budget-wait share, delivery
    # rate) and moves streams / window size / prefetch depth between
    # windows — DEMODEL_TUNER=0 keeps every knob at its fixed default
    tuner = (tuner_mod.PullTuner(budget=budget,
                                 prefetch_depth=prefetch_depth).start()
             if tuner_mod.tuner_enabled() else None)

    def fetch(job, idx):
        reader, key, name, spec = job
        nbytes = spec.end - spec.start
        with trace.span("prefetch-fetch", tensor=name, bytes=nbytes,
                        job=idx):
            # the admission-ticket wait + budget charge together are the
            # "waiting for RAM" stage of a slow pull — own span so the
            # critical path can name it
            with trace.span("budget-wait", bytes=nbytes):
                with admit_cv:
                    while admission["next"] != idx \
                            and not admission["dead"]:
                        admit_cv.wait()
                got = False
                try:
                    # charge before the bytes exist, so a worker blocks
                    # HERE rather than allocating past the budget;
                    # released after place()
                    budget.acquire(nbytes)
                    got = True
                finally:
                    try:
                        with admit_cv:
                            admission["next"] = idx + 1
                            admit_cv.notify_all()
                    except BaseException:
                        # the ticket is held by now: a raise on the
                        # hand-over path must give it back or the
                        # budget is down nbytes forever
                        if got:
                            budget.release(nbytes)
                        raise
            try:
                buf = np.empty(nbytes, dtype=np.uint8)
                tuner_mod.fetch_windows(reader, key, buf, spec.start,
                                        tuner)
            except BaseException:
                budget.release(nbytes)
                raise
            return buf

    def place(buf, name, spec):
        mv = memoryview(buf)
        start = spec.start

        def read_at(off, ln, _mv=mv, _s=start):
            return _mv[off - _s:off - _s + ln]

        np_dtype = _np_dtype(spec.dtype)
        if name in out.arrays:
            raise ValueError(f"duplicate tensor across shards: {name}")
        sharding = plan.sharding_for(name, spec.shape, np_dtype.itemsize)
        with trace.span("place", tensor=name, bytes=buf.nbytes):
            out.arrays[name] = place_tensor(
                read_at, spec.shape, np_dtype, spec.start, sharding,
                cast_to)

    # phase accounting (exposed via the pull report): fetch wall vs
    # place wall tells whether a slow pull is network-bound or
    # device-transfer-bound — on a tunneled single-chip backend the two
    # differ by an order of magnitude and the split is the diagnosis.
    # Under prefetch overlap the first key is the EXPOSED stall on the
    # next buffer (overlapped network time hides inside place), so it is
    # named fetch_stall_secs there, not fetch_secs.
    fetch_key = "fetch_secs" if prefetch_depth == 0 else "fetch_stall_secs"
    phases = {fetch_key: 0.0, "place_secs": 0.0}
    out.phase_secs = phases

    if prefetch_depth == 0:
        # thread-free: fetch inline, place, next — the fastest shape
        # when there is no core to hide the fetch on
        try:
            for i, (reader, key, name, spec) in enumerate(jobs):
                t0 = time.perf_counter()
                try:
                    buf = fetch((reader, key, name, spec), i)
                except OSError as e:
                    raise PipelineFailure(e, out) from e
                t1 = time.perf_counter()
                try:
                    place(buf, name, spec)
                finally:
                    budget.release(buf.nbytes)
                t2 = time.perf_counter()
                phases[fetch_key] += t1 - t0
                phases["place_secs"] += t2 - t1
        finally:
            if tuner is not None:
                tuner.stop()
        return out

    # with a live tuner the pool is sized to the prefetch CEILING and the
    # submit loop keeps only the tuner's CURRENT depth in flight — depth
    # changes apply between jobs, never mid-fetch
    pool_size = tuner.max_prefetch if tuner is not None else prefetch_depth
    with ThreadPoolExecutor(max_workers=max(1, pool_size)) as ex:
        # the try must live INSIDE the `with`: on an exception the
        # executor's __exit__ joins its workers during unwinding, so a
        # worker blocked in budget.acquire has to be woken by abort()
        # BEFORE that join runs — an outer handler would run after it,
        # i.e. after the deadlock
        try:
            # trace.wrap: executor threads don't inherit contextvars, so
            # capture the pull span's context at the submit site
            pending: list = []
            next_job = 0

            def top_up() -> None:
                nonlocal next_job
                depth = (max(1, min(tuner.prefetch_depth, pool_size))
                         if tuner is not None else prefetch_depth)
                while len(pending) < depth and next_job < len(jobs):
                    pending.append(ex.submit(trace.wrap(fetch),
                                             jobs[next_job], next_job))
                    next_job += 1

            top_up()
            for i, (reader, key, name, spec) in enumerate(jobs):
                t0 = time.perf_counter()
                try:
                    buf = pending.pop(0).result()
                except OSError as e:
                    # surface WHAT already landed: placed tensors are
                    # final (their bytes are verified views of fetched
                    # windows) — the failover path resumes from them
                    for p in pending:
                        p.cancel()
                    raise PipelineFailure(e, out) from e
                t1 = time.perf_counter()
                top_up()
                try:
                    place(buf, name, spec)
                finally:
                    budget.release(buf.nbytes)
                phases[fetch_key] += t1 - t0
                phases["place_secs"] += time.perf_counter() - t1
        except BaseException:
            # in-flight buffers die with this call; their charges are
            # moot. Wake BOTH wait states before the executor join:
            # acquire-waiters via abort, ticket-waiters via "dead"
            budget.abort()
            with admit_cv:
                admission["dead"] = True
                admit_cv.notify_all()
            raise
        finally:
            if tuner is not None:
                tuner.stop()
    return out


def pull_manifest_to_hbm(
    model: str,
    peers: list[str],
    mesh=None,
    plan: ShardingPlan | None = None,
    source: str = "hf",
    cast_to=None,
    ici_complete: bool | None = None,
    streams: int | None = None,
    swarm: "SwarmScheduler | None" = None,
):
    """Place ``model`` into HBM straight off a warm peer, shard-reads only.

    ``swarm``: a started-or-startable :class:`SwarmScheduler` makes this
    a swarm-mode cold pull — this host fetches only its ring-owned chunk
    set from the warm-peer rotation and cross-fills the rest from its
    swarm siblings (aggregate origin bytes ≈ 1× the manifest across the
    pod, not N×). The caller owns the scheduler lifecycle: keep it open
    until the whole pod is done, then ``close()`` it.

    Every host of a ``jax.distributed`` pod calls this with the same
    arguments; each fetches only its devices' byte windows over DCN and
    replicated tensors complete over ICI (each host reads 1/N). Returns
    ``(report, Placement)`` where ``report["network_bytes"]`` is THIS
    host's DCN byte count — the pod-delivery proof asserts it is a strict
    fraction of the checkpoint.

    Weight files deliver in manifest order (identical on every host), so
    cross-host collectives pair deterministically — see module docstring.
    """
    import os

    from demodel_tpu.parallel.mesh import make_mesh

    if mesh is None:
        mesh = make_mesh()
    if plan is None:
        plan = ShardingPlan(mesh)
    profile_dir = os.environ.get("DEMODEL_PROFILE_DIR", "").strip()
    profiling = False
    if profile_dir:
        # SURVEY §5 tracing: same jax.profiler window the whole-file pull
        # gets — open in xprof to see window fetch vs device transfer
        try:
            import jax.profiler as _profiler

            _profiler.start_trace(profile_dir)
            profiling = True
        except Exception as e:  # noqa: BLE001 — tracing must never break a pull
            log.warning("jax.profiler trace not started: %s", e)
    try:
        # the ROOT span of a sharded pull: every window read, budget
        # wait, retry and failover below stitches under this trace id —
        # and across hosts via the traceparent the wire calls carry
        with trace.span("pull", model=model, source=source,
                        swarm=(swarm.self_id if swarm else None)):
            return _pull_manifest_to_hbm(model, peers, mesh, plan, source,
                                         cast_to, ici_complete, streams,
                                         swarm)
    finally:
        if profiling:
            try:
                import jax.profiler as _profiler

                _profiler.stop_trace()
                log.info("sharded-pull trace written to %s", profile_dir)
            except Exception as e:  # noqa: BLE001
                log.warning("jax.profiler stop_trace failed: %s", e)


def _pull_manifest_to_hbm(model, peers, mesh, plan, source, cast_to,
                          ici_complete, streams, swarm=None):
    import jax

    from demodel_tpu.sink.hbm import deliver_safetensors

    t0 = time.perf_counter()
    peer, manifest = fetch_manifest(peers, model, source=source)
    placement = Placement(mesh_desc=f"{dict(mesh.shape)}")
    report: dict = {
        "name": model, "source": source, "peer": peer,
        "files": list(manifest.get("files", [])),
        "network_bytes": 0, "weight_bytes": 0,
    }
    readers: list[PeerBlobReader] = []
    # Peer policy, single-process: files stripe round-robin over the
    # RESPONSIVE peers (pipelined path below rotates the primary per
    # file), with the rest of the order as failover — a header/window
    # failure retries the file (or, mid-pipeline, rebuilds via the
    # per-file path). Peers are liveness-probed once up front with a
    # short deadline so a hung-but-accepting peer (the wedged-tunnel
    # shape) never lands on the critical path at its full read timeout.
    # Multi-host meshes pin everything to the manifest peer and re-raise
    # on failure: a host that locally retried a file whose earlier
    # tensors already ran their redistribute() collectives would re-issue
    # them while other hosts sit in later ones — same-shaped tensors
    # would pair silently wrong, different shapes deadlock; the caller
    # restarts the pull pod-wide instead.
    if jax.process_count() == 1:
        others = [p.rstrip("/") for p in peers if p.rstrip("/") != peer]
        peer_order = [peer] + _responsive_peers(others)
    else:
        peer_order = [peer]
    weight_files = []
    for f in manifest.get("files", []):
        if not is_weight_file(f["name"], f.get("media_type", "")):
            continue
        if int(f.get("size") or 0) <= 0:
            raise IOError(f"manifest entry {f['name']} lacks a size")
        weight_files.append(f)

    # single-process safetensors: one prefetch pipeline over ALL tensors
    # of ALL files in manifest order — tensor N's device transfer overlaps
    # tensor N+1..N+depth's downloads with no bubble at file boundaries
    pipelined = False
    resume_skip: set = set()       # tensors placed by a failed pipeline
    file_tensors: dict = {}        # file key → its tensor names
    if (jax.process_count() == 1
            and weight_files
            and all(f["name"].endswith(".safetensors")
                    for f in weight_files)):
        try:
            jobs = []
            health = PeerHealth.shared()
            # files stripe over the RESPONSIVE peers by consistent hash
            # with BOUNDED LOADS: every host computes the same file→peer
            # primary from the same ring+capacity walk, so the striping
            # needs no rotation counter — and no peer's primary share
            # exceeds ceil(files/N) (pure ring ownership is lumpy on a
            # small file set; a capacity-spilled file's primary is still
            # on its ring succession, so PeerSet.locate's ring-first
            # guess misses at most into its probe fallback). The rest of
            # the ring order is the failover rotation; peers whose
            # breaker opened mid-pull drop out HERE — a peer that died
            # at file 3 must not greet files 4..N with a full
            # read-timeout each (it re-enters via its half-open probe
            # once the cooldown elapses)
            stripe_ring = HashRing(peer_order)
            stripe = bounded_assign(
                stripe_ring, [f["key"] for f in weight_files])
            for f in weight_files:
                primary = stripe.get(f["key"]) or peer_order[0]
                rotated = [primary] + [p for p in peer_order
                                       if p != primary]
                reader, index = _reader_and_index(
                    f, health.healthy(rotated), streams)
                fkey, fsize = f["key"], int(f["size"])
                file_tensors[fkey] = set(index.tensors)
                if swarm is not None:
                    swarm.add_file(fkey, fsize, reader)
                    reader = SwarmBlobReader(swarm, fkey, fsize, reader)
                readers.append(reader)
                for tname, spec in index.tensors.items():
                    jobs.append((reader, fkey, tname, spec))
            if swarm is not None:
                swarm.start()
            delivered = _deliver_jobs_pipelined(
                jobs, mesh, plan, cast_to=cast_to)
            merge_placement(placement, delivered)
            report["phase_secs"] = delivered.phase_secs
            report["weight_bytes"] += sum(int(f["size"])
                                          for f in weight_files)
            pipelined = True
        except PipelineFailure as e:
            # mid-pipeline peer failure: keep every tensor that already
            # landed (their bytes are verified fetched windows) and let
            # the per-file failover below deliver ONLY the missing ones
            # — a flaky window at shard 14 of 15 costs the remaining
            # windows, not a full redo of the device transfers
            merge_placement(placement, e.partial)
            # the phase split for what DID land — the flaky-pull case is
            # exactly where the fetch/place diagnosis matters most. The
            # resumed remainder below accumulates no phase timings, so
            # flag the split as partial: a consumer summing phase_secs
            # against wall-clock must not mistake pre-failure seconds
            # for the whole pull's
            report["phase_secs"] = e.partial.phase_secs
            report["phase_secs_partial"] = True
            resume_skip = set(e.partial.arrays)
            log.warning("pipelined delivery failed (%s); %d tensors "
                        "landed — resuming the rest with per-file "
                        "failover", e.cause, len(resume_skip))
            report["weight_bytes"] = 0
        except OSError as e:
            # failure outside the pipeline loop (header/index reads):
            # nothing landed, full per-file fallback
            log.warning("pipelined delivery failed (%s); retrying with "
                        "per-file failover", e)
            placement = Placement(mesh_desc=f"{dict(mesh.shape)}")
            report["weight_bytes"] = 0

    if not pipelined:
        from demodel_tpu.sink.hbm import deliver_gguf

        for f in weight_files:
            name, key = f["name"], f["key"]
            size = int(f["size"])
            if resume_skip and key in file_tensors \
                    and file_tensors[key] <= resume_skip:
                # every tensor of this file survived the failed pipeline:
                # no reader, no header re-fetch, bytes already accounted
                report["weight_bytes"] += size
                continue
            placed = None
            last_err: Exception | None = None
            retry_order = PeerHealth.shared().healthy(peer_order)
            for pi, source_peer in enumerate(retry_order):
                reader = PeerBlobReader(
                    source_peer, key, size, streams=streams,
                    failover=retry_order[pi + 1:] + retry_order[:pi])
                try:
                    if name.endswith(".safetensors"):
                        # skip ONLY the resume survivors — skipping the
                        # whole accumulated placement would silently
                        # disable the cross-shard duplicate-tensor guard
                        placed = deliver_safetensors(
                            reader, key, mesh=mesh, plan=plan,
                            cast_to=cast_to, ici_complete=ici_complete,
                            skip=resume_skip)
                    else:
                        placed = deliver_gguf(reader, key, mesh=mesh,
                                              plan=plan)
                    readers.append(reader)
                    break
                except (OSError, ValueError) as e:
                    # OSError: transport (incl. requests exceptions mapped
                    # by the reader); ValueError: corrupt header bytes
                    last_err = e
                    readers.append(reader)  # count wasted bytes honestly
                    log.warning("delivery of %s from %s failed (%s); "
                                "trying next peer", name, source_peer, e)
            if placed is None:
                raise IOError(f"no peer could serve {name}") from last_err
            merge_placement(placement, placed)
            report["weight_bytes"] += size
    t_block = time.perf_counter()
    # demodel: allow(no-host-sync-in-hot-path) — the pod pull's single
    # end-of-delivery sync: block_secs is reported, and every device
    # transfer has already been dispatched when we get here
    jax.block_until_ready(list(placement.arrays.values()))
    report["block_secs"] = round(time.perf_counter() - t_block, 3)
    report["network_bytes"] = sum(r.bytes_fetched for r in readers)
    report["secs"] = round(time.perf_counter() - t0, 3)
    log.info("pod-placed %d tensors (%.1f MB weights) from %s: this host "
             "fetched %.1f MB over DCN in %.2fs",
             len(placement.arrays), report["weight_bytes"] / 1e6, peer,
             report["network_bytes"] / 1e6, report["secs"])
    return report, placement


def materialize_aux_files(manifest: dict, peer: str, dest,
                          timeout: float = 60.0) -> list:
    """Fetch the small non-weight files (config/tokenizer/index) of a
    peer-held model into ``dest`` — consumers (`transformers`) need them
    on disk next to nothing else; weight bytes stay on the wire→HBM path."""
    from pathlib import Path

    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    s = requests.Session()
    health = PeerHealth.shared()
    policy = RetryPolicy()
    out = []
    for f in manifest.get("files", []):
        if is_weight_file(f["name"], f.get("media_type", "")):
            continue
        r = request_with_retry(
            s, "GET", f"{peer}/peer/object/{f['key']}",
            policy=policy, health=health, peer=peer.rstrip("/"),
            timeout=timeout, what=f"aux file {f['name']}")
        p = dest / f["name"].replace("/", "_")
        p.write_bytes(r.content)
        out.append(p)
    return out
