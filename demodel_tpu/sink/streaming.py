"""Streaming sink: land shards in HBM *while* later shards still download.

The reference's delivery is strictly download-then-load (proxy caches bytes,
a foreign client loads them afterwards). The rebuild overlaps the two: the
registry's parallel fetch workers hand each completed weight file to this
sink (``on_file``), a dedicated worker turns it into sharded device arrays
(range reads → ``device_put`` under the plan's ``NamedSharding``), and the
north-star clock "cold pull → HBM" pays max(network, PCIe/ICI) instead of
their sum.

One worker thread is deliberate: host→device transfer for one chip
serializes on the transfer engine anyway, and a single consumer keeps
``jax`` dispatch single-threaded while fetch threads stay pure-network.

Host RAM is bounded: artifacts that carry landing buffers (memory-first
peer fetch) count against ``DEMODEL_SINK_BUFFER_MB``; ``submit`` blocks
fetch workers once the admitted-but-undelivered window would exceed it,
so peak host RAM stays at the in-flight window — never the whole model
(a 70B/15-shard pull must not need 140 GB of host RAM).
"""

from __future__ import annotations

import os
import queue
import threading
import weakref

from jax.sharding import Mesh

from demodel_tpu.sink.hbm import (
    Placement,
    deliver_file,
    is_weight_file,
    merge_placement,
)
from demodel_tpu.sink.plan import ShardingPlan
from demodel_tpu.store import Store
from demodel_tpu.parallel.mesh import make_mesh
from demodel_tpu.utils import trace
from demodel_tpu.utils.env import env_int
from demodel_tpu.utils.logging import get_logger

log = get_logger("sink.streaming")

_DONE = object()


class ByteBudget:
    """Counting semaphore in BYTES for landing buffers.

    Shared between the fetcher (charges at buffer ALLOCATION — the moment
    host RAM is actually committed) and the streaming sink (releases once
    the buffer's tensors are resident on device). Waiting happens at
    allocation, so N fetch workers cannot pin N full shards regardless of
    queue bounds. A single item larger than the budget is admitted alone
    rather than deadlocking.

    Every live budget sits in a weak registry so ``/debug/statusz`` can
    report in-use / high-water per budget without the sink layer knowing
    anything about the introspection surface.
    """

    def __init__(self, max_bytes: int, name: str = "sink"):
        self.max_bytes = max_bytes
        self.name = name
        self._in_use = 0
        self.high_water = 0
        self.waiters = 0
        self._cv = threading.Condition()
        self._aborted = False
        with _budget_registry_lock:
            _budget_registry.add(self)

    @property
    def in_use(self) -> int:
        with self._cv:
            return self._in_use

    def acquire(self, nbytes: int) -> None:
        with self._cv:
            self.waiters += 1
            try:
                while (self._in_use > 0
                       and self._in_use + nbytes > self.max_bytes
                       and not self._aborted):
                    # pure wait: every state change that can unblock this
                    # predicate (release, abort) notify_all()s, so no timeout
                    # poll is needed — waiters wake on the event, not 0.2s late
                    self._cv.wait()
            finally:
                self.waiters -= 1
            self._in_use += nbytes
            if self._in_use > self.high_water:
                self.high_water = self._in_use

    def release(self, nbytes: int) -> None:
        with self._cv:
            self._in_use -= nbytes
            self._cv.notify_all()

    def abort(self) -> None:
        """Unblock all waiters (error path — delivery is being abandoned)."""
        with self._cv:
            self._aborted = True
            self._cv.notify_all()

    def describe(self) -> dict:
        """statusz snapshot: capacity, live charge, high-water, blocked
        acquirers — "is the pull stuck on admission" at a glance."""
        with self._cv:
            return {"name": self.name, "max_bytes": self.max_bytes,
                    "in_use_bytes": self._in_use,
                    "high_water_bytes": self.high_water,
                    "waiters": self.waiters, "aborted": self._aborted}


#: weak set of live budgets — statusz iterates it; a collected budget
#: (pull finished, sink dropped) falls out on its own
_budget_registry_lock = threading.Lock()
_budget_registry: "weakref.WeakSet[ByteBudget]" = weakref.WeakSet()


def budgets_snapshot() -> list[dict]:
    """Live budgets, described — the statusz "budgets" section."""
    with _budget_registry_lock:
        budgets = list(_budget_registry)
    return sorted((b.describe() for b in budgets),
                  key=lambda d: str(d["name"]))


class _Cancelled(Exception):
    """Internal sentinel: drain the queue without delivering."""


class StreamingSink:
    """Consumes completed FileArtifacts, delivers weight files to HBM.

    Thread-safe producer side (``submit`` may be called from any fetch
    worker); ``finish()`` drains the queue, joins the worker, re-raises the
    first delivery error, and returns the merged :class:`Placement`.
    """

    def __init__(self, store: Store, mesh: Mesh | None = None,
                 plan: ShardingPlan | None = None, cast_to=None,
                 overlap: bool | None = None,
                 max_buffered_bytes: int | None = None,
                 budget: ByteBudget | None = None):
        self.store = store
        self.mesh = mesh if mesh is not None else make_mesh()
        self.plan = plan if plan is not None else ShardingPlan(self.mesh)
        self.cast_to = cast_to
        self.placement = Placement(mesh_desc=f"{dict(self.mesh.shape)}")
        self._q: queue.Queue = queue.Queue()
        self._err: BaseException | None = None
        self._err_lock = threading.Lock()  # _err written from worker + caller
        if overlap is None:
            # device_put dispatch is a host memcpy that releases the GIL,
            # so overlapping it with the (native, GIL-free) fetch pays even
            # on a single-core host — measured: serializing them was the
            # bulk of the r02 throughput regression
            env = os.environ.get("DEMODEL_SINK_OVERLAP", "").strip().lower()
            overlap = env not in ("0", "false", "no", "off")
        self.overlap = overlap
        if max_buffered_bytes is None:
            max_buffered_bytes = env_int("DEMODEL_SINK_BUFFER_MB", 1024,
                                         minimum=1) << 20
        #: shared with the fetcher when delivery wires one (charging then
        #: happens at buffer allocation); standalone sinks charge at submit
        self.budget = budget if budget is not None else ByteBudget(
            max_buffered_bytes)
        self._worker = None
        self._worker_lock = threading.Lock()
        if overlap:
            self._start_worker()

    def _start_worker(self) -> None:
        with self._worker_lock:
            if self._worker is None:
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()

    # ---- producer side (fetch threads)
    def submit(self, artifact) -> None:
        """Queue a completed artifact; non-weight files are ignored. An
        artifact carrying a landing ``buffer`` (memory-first peer fetch) is
        delivered from host memory without touching the store.

        Blocks (backpressuring the fetch worker) while the admitted landing
        buffers exceed ``max_buffered`` — the queue is bounded in *bytes*,
        not items, because items span 44 bytes to multi-GB shards."""
        name = artifact.name if hasattr(artifact, "name") else artifact["name"]
        media = (artifact.media_type if hasattr(artifact, "media_type")
                 else artifact.get("media_type", ""))
        if not is_weight_file(name, media):
            # a charged buffer the sink will never consume (config/tokenizer
            # fetched memory-first) returns its budget immediately
            skipped = getattr(artifact, "buffer", None)
            if skipped is not None and getattr(artifact, "budget_charged",
                                               False):
                self.budget.release(int(skipped.nbytes))
            return
        key = artifact.key if hasattr(artifact, "key") else artifact["key"]
        buffer = getattr(artifact, "buffer", None)
        nbytes = int(getattr(buffer, "nbytes", 0)) if buffer is not None else 0
        if nbytes:
            # a buffered artifact always needs a live consumer: deferred
            # (no-overlap) mode would otherwise hold every landing buffer
            # until finish() — the unbounded-RAM failure mode
            self._start_worker()
            if not getattr(artifact, "budget_charged", False):
                # standalone producers charge here; fetchers sharing the
                # budget charged at allocation (the earlier, correct point)
                with trace.span("sink-budget-wait", file=name, bytes=nbytes):
                    self.budget.acquire(nbytes)
        # the sink worker is another thread, outside the submitting fetch
        # span's contextvars — carry the parent across the queue as a
        # traceparent so sink-deliver stitches into the pull trace, and
        # carry the head-sampling verdict too (a sampled-out pull must not
        # leak orphan sink-deliver roots from the worker side)
        self._q.put((name, key, buffer, nbytes, trace.traceparent(),
                     trace.subtree_suppressed()))

    # ---- consumer side
    def _set_err(self, e: BaseException) -> None:
        with self._err_lock:
            if self._err is None:
                self._err = e
        self.budget.abort()  # unblock backpressured producers

    def _get_err(self) -> BaseException | None:
        with self._err_lock:
            return self._err

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is _DONE:
                return
            name, key, buffer, nbytes, parent, suppressed = item
            try:
                if self._get_err() is not None:
                    continue  # drain without working after first failure
                try:
                    # ici_complete=False: delivery order here follows fetch
                    # completion, which is NOT synchronized across hosts —
                    # a cross-host collective from this thread would pair
                    # with a different tensor's collective on another host.
                    # Multi-host pulls that want the ICI leg use the
                    # manifest-ordered sharded pod path instead
                    # (demodel_tpu.sink.remote.pull_manifest_to_hbm), where
                    # per-host reads are window-sized from the start and
                    # collective order is deterministic by construction.
                    deliver_span = (trace.NOOP if suppressed else
                                    trace.span("sink-deliver",
                                               remote_parent=parent,
                                               file=name, bytes=nbytes))
                    with deliver_span as sp:
                        placed = deliver_file(self.store, name, key,
                                              self.mesh, self.plan,
                                              self.cast_to, buffer=buffer,
                                              ici_complete=False)
                        sp.set_attr("tensors", len(placed.arrays))
                    merge_placement(self.placement, placed)
                    log.debug("streamed %s → %d tensors", name,
                              len(placed.arrays))
                except BaseException as e:  # noqa: BLE001 — reported at finish()
                    self._set_err(e)
            finally:
                if nbytes:
                    self.budget.release(nbytes)

    def cancel(self) -> None:
        """Abandon delivery: drain queued files without doing the work.
        Used on the pull-error path, where the placement would be discarded."""
        self._set_err(_Cancelled())
        self._q.put(_DONE)
        if self._worker is not None:
            self._worker.join()

    def finish(self, block: bool = True) -> Placement:
        """Wait for every queued file to land; return the merged placement."""
        self._q.put(_DONE)
        if self._worker is not None:
            self._worker.join()
        else:
            self._run()  # deferred mode: deliver everything now, fetch done
        err = self._get_err()
        if isinstance(err, _Cancelled):
            # the private sentinel must not escape to callers
            raise RuntimeError("sink was cancelled before finish()")
        if err is not None:
            raise err
        if block and self.placement.arrays:
            import jax

            # demodel: allow(no-host-sync-in-hot-path) — finish(block=True)
            # IS the delivery's documented sync point: the caller asked for
            # resident arrays, so the one sync happens here, after all
            # transfers were dispatched
            jax.block_until_ready(list(self.placement.arrays.values()))
        log.info("streamed %d tensors (%.1f MB) onto mesh %s",
                 len(self.placement.arrays),
                 self.placement.total_bytes / 1e6, self.placement.mesh_desc)
        return self.placement
