"""Closed-loop adaptive pull tuning fed by live telemetry quantiles.

The pull plane's knobs — per-peer stream concurrency, fetch window size,
prefetch depth — ship as fixed env defaults, and ROADMAP's adaptive-tuning
item asks for them to move with OBSERVED stage times instead. This module
is the first consumer of the telemetry time-series plane
(:mod:`demodel_tpu.utils.metrics`): an AIMD-style controller thread that,
while a pull runs, reads the sliding-window signals the plane already
serves —

- ``stage_duration_seconds{span="window-read"}`` windowed p99 (is the
  wire leg degrading?),
- ``peer_retries_total`` family rate + open circuit breakers (is the
  link faulting?),
- the ``budget-wait`` share of wall time (is admission, i.e. host RAM,
  the bottleneck?),
- ``pull_bytes_total`` rate (the delivery rate the whole loop optimizes)

— and adjusts the knobs between windows, congestion-control style
(BBR-ish probing: raise one knob, keep the raise only if the delivery
rate held; multiplicative back-off on wire faults). Every decision lands
as an event on the tuner's own root span AND as ``tuner_*`` gauges +
``tuner_decisions_total`` on the scrape, so the tuner is itself fully
observable: ``/debug/statusz`` shows the live knob values (source
``tuner`` in the effective-config section) and ``/debug/telemetry``
shows the signals it acted on.

``DEMODEL_TUNER=0`` disables the controller entirely — every knob then
keeps its fixed env/default resolution, byte-for-byte the pre-tuner
behavior. Increases are bounded by the same :class:`~demodel_tpu.sink
.streaming.ByteBudget` charging discipline the pipelined fetch already
enforces: a prefetch raise is only attempted when the budget has
headroom, and even a wrong raise just blocks in ``acquire`` instead of
over-committing host RAM.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from demodel_tpu.utils import metrics, trace
from demodel_tpu.utils.env import env_float, env_int
from demodel_tpu.utils.logging import get_logger

log = get_logger("sink.tuner")

#: the telemetry families the controller reads — literal names so the
#: metric-hygiene analyzer rule can check them against the families the
#: tree actually registers (a typo here silently reads an empty window)
_WINDOW_READ = metrics.labeled("stage_duration_seconds", span="window-read")
_BUDGET_WAIT = metrics.labeled("trace_span_seconds_total",
                               span="budget-wait")
#: the device-plane signals (ROADMAP: "read device-side place/stage
#: histograms"): how long placing a landed buffer onto the accelerator
#: takes, end-to-end per sink delivery
_PLACE = metrics.labeled("stage_duration_seconds", span="place")
_SINK_DELIVER = metrics.labeled("stage_duration_seconds",
                                span="sink-deliver")


def tuner_enabled() -> bool:
    """The ``DEMODEL_TUNER`` switch: on unless explicitly disabled —
    ``=0`` restores the fixed env defaults everywhere."""
    from demodel_tpu.utils.env import tuner_enabled as _enabled

    return _enabled()


def _default_window_bytes() -> int:
    """Initial (and untuned-path) fetch window (resolution lives in
    utils.env so the dep-light statusz surface reports the same
    default)."""
    from demodel_tpu.utils.env import default_pull_window_mb

    return default_pull_window_mb() << 20


def fetch_windows(reader: Any, key: str, buf: Any, offset: int,
                  tuner: "PullTuner | None") -> int:
    """Fill ``buf`` from ``reader`` starting at ``offset``, split into
    tuner-sized sub-windows when a tuner is live (each sub-window is one
    ``window-read`` span — the unit the p99 signal and the retry cost
    are both functions of). Without a tuner this is exactly one
    ``pread_into`` — the untuned path stays byte-identical to before."""
    view = memoryview(buf).cast("B")
    nbytes = view.nbytes
    if tuner is None:
        return reader.pread_into(key, view, offset)
    pos = 0
    while pos < nbytes:
        # re-read the live knobs per window: the controller adjusts them
        # BETWEEN windows, never mid-transfer
        if hasattr(reader, "streams"):
            reader.streams = tuner.streams
        step = min(nbytes - pos, max(1, tuner.window_bytes))
        reader.pread_into(key, view[pos:pos + step], offset + pos)
        pos += step
    return nbytes


# ------------------------------------------------------------ controller


class PullTuner:
    """One pull's adaptive controller. Start with :meth:`start`, stop in
    a ``finally`` — the thread is short-lived (the pull's duration) and
    joined on stop. All knob reads are plain attribute loads (ints are
    GIL-atomic), so the fetch hot path pays nothing for adaptivity.

    Test seams: ``telemetry``/``health``/``clock``/``sleep`` injectable;
    :meth:`tick` is callable directly (no thread) with forced signals.
    """

    def __init__(self, budget: Any = None, prefetch_depth: int | None = None,
                 telemetry: "metrics.Telemetry | None" = None,
                 health: Any = None,
                 tick_s: float | None = None,
                 window_s: float | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] | None = None):
        from demodel_tpu.parallel.peer import _peer_streams

        self._budget = budget
        self._telemetry = telemetry
        self._health = health
        self.tick_s = tick_s if tick_s is not None else env_int(
            "DEMODEL_TUNER_TICK_MS", 500, minimum=50) / 1000.0
        self.window_s = window_s if window_s is not None else float(env_int(
            "DEMODEL_TUNER_WINDOW_S", 30, minimum=1))
        self._clock = clock
        self._stop = threading.Event()
        self._sleep = sleep if sleep is not None else self._stop.wait

        # knobs start at the exact fixed defaults the untuned path uses
        self.streams = _peer_streams()
        self.window_bytes = _default_window_bytes()
        init_pref = 0 if prefetch_depth is None else int(prefetch_depth)
        self.prefetch_depth = init_pref

        # bounds: never below the floor a working pull needs, never past
        # the point extra concurrency stops paying (per-peer politeness)
        self.min_streams, self.max_streams = 1, max(8, self.streams)
        self.min_window = 2 << 20
        self.max_window = max(self.window_bytes, 256 << 20)
        # a pull resolved to prefetch 0 (single-core, CPU backend) keeps
        # it: the measured regression there is contention, not tuning
        self.min_prefetch = 0 if init_pref == 0 else 1
        self.max_prefetch = 0 if init_pref == 0 else max(4, init_pref)

        # AIMD state
        self.retry_hi = env_float("DEMODEL_TUNER_RETRY_HI", 0.25)  # /s
        #: device-plane pressure thresholds: a windowed place/sink-deliver
        #: p99 above place_hi seconds, or the ByteBudget charged past
        #: hbm_hi of its cap, sheds prefetch — depth is the knob that
        #: converts device-side latency/HBM pressure into admission relief
        self.place_hi = env_float("DEMODEL_TUNER_PLACE_HI", 1.0)  # seconds
        self.hbm_hi = env_float("DEMODEL_TUNER_HBM_HI", 0.85)  # share
        #: how long a live probe settles before being judged: the
        #: keep/revert test must read a window that POST-DATES the raise
        #: — judged one tick later against the window_s moving average,
        #: a 0.5 s tick can move a 30 s average by at most ~1.7%, so the
        #: revert branch would be arithmetically dead and every probe
        #: would be kept even when the raise hurt
        self.judge_s = max(4 * self.tick_s, 2.0)
        self.decisions = 0
        self._best_thr = 0.0
        self._probe: tuple[str, int] | None = None  # (knob, previous value)
        self._probe_base = 0.0
        self._probe_t = 0.0
        self._hold_until = 0.0
        self._round_robin = 0
        self._thread: threading.Thread | None = None
        self._span: Any = trace.NOOP
        #: serializes the tick thread's knob/bookkeeping WRITES against
        #: snapshot() (the statusz/bench read surface): without it a
        #: reader could see decision N's count with decision N-1's knob
        #: values — a torn document (guarded-field finding, PR 10). The
        #: fetch hot path (fetch_windows) deliberately stays lock-free:
        #: its per-window int loads are GIL-atomic and individually
        #: consistent, which is all a window split needs.
        self._knob_lock = threading.Lock()

    # -- wiring ---------------------------------------------------------
    def _tel(self) -> "metrics.Telemetry":
        return self._telemetry if self._telemetry is not None \
            else metrics.HUB.telemetry()

    def _breaker_open(self) -> bool:
        health = self._health
        if health is None:
            from demodel_tpu.utils.faults import PeerHealth

            health = PeerHealth._shared  # noqa: SLF001 — observe, never
            # allocate: a pull that made no wire call has no breakers
            if health is None:
                return False
        return any(b.get("state") != "closed"
                   for b in health.describe().values())

    def snapshot(self) -> dict[str, Any]:
        """Live knob values + controller state (statusz / bench) — one
        CONSISTENT document: taken under the same lock the tick thread
        writes under, so the decision count always matches the knob
        values it produced."""
        with self._knob_lock:
            return {
                "streams": self.streams,
                "window_bytes": self.window_bytes,
                "window_mb": self.window_bytes >> 20,
                "prefetch_depth": self.prefetch_depth,
                "decisions": self.decisions,
                "best_throughput_bps": round(self._best_thr, 1),
            }

    @property
    def window_mb(self) -> int:
        with self._knob_lock:
            return self.window_bytes >> 20

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "PullTuner":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run,
                                        name="pull-tuner", daemon=True)
        _register(self)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
            self._thread = None
        _unregister(self)

    def _run(self) -> None:
        # the tuner's own root span: open for the pull's duration (so a
        # stuck pull's statusz shows the controller and its live knobs),
        # every decision an event on it
        with trace.span("tuner", streams=self.streams,
                        window_mb=self.window_mb,
                        prefetch=self.prefetch_depth) as sp:
            self._span = sp
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — the tuner must
                    # never take the pull down; knobs just stop moving
                    log.warning("tuner tick failed: %s", e)
                self._sleep(self.tick_s)
            sp.set_attr("decisions", self.decisions)
            self._span = trace.NOOP

    # -- the control loop ----------------------------------------------
    def _gauges(self, thr: float) -> None:
        metrics.HUB.set_gauge("tuner_streams", self.streams)
        metrics.HUB.set_gauge("tuner_window_bytes", self.window_bytes)
        metrics.HUB.set_gauge("tuner_prefetch_depth", self.prefetch_depth)
        metrics.HUB.set_gauge("tuner_throughput_bps", round(thr, 1))

    def _decide(self, action: str, knob: str, frm: Any, to: Any,
                reason: str) -> None:
        self.decisions += 1
        self._span.event("tune", action=action, knob=knob, frm=frm, to=to,
                         reason=reason)
        metrics.HUB.inc(metrics.labeled("tuner_decisions_total",
                                        action=action))
        log.info("tuner %s %s: %s -> %s (%s)", action, knob, frm, to,
                 reason)

    def _backoff(self, reason: str) -> None:
        """Multiplicative decrease on a wire-fault signal: the link is
        telling us we are over-driving it."""
        if self.streams > self.min_streams:
            new = max(self.min_streams, self.streams // 2)
            self._decide("decrease", "streams", self.streams, new, reason)
            self.streams = new
        if self.window_bytes > self.min_window:
            new = max(self.min_window, self.window_bytes // 2)
            self._decide("decrease", "window_bytes", self.window_bytes,
                         new, reason)
            self.window_bytes = new
        if self.prefetch_depth > max(1, self.min_prefetch):
            new = self.prefetch_depth - 1
            self._decide("decrease", "prefetch_depth",
                         self.prefetch_depth, new, reason)
            self.prefetch_depth = new
        self._probe = None
        self._best_thr *= 0.5  # the old best is stale on a faulting link
        self._hold_until = self._clock() + 4 * self.tick_s

    def _budget_pressure(self) -> float:
        """The live HBM/host-RAM admission pressure: the ByteBudget's
        in-use share of its cap (0.0 without a budget — an unthrottled
        pull has no device-side admission signal to read)."""
        budget = self._budget
        if budget is None:
            return 0.0
        try:
            cap = float(budget.max_bytes)
            if cap <= 0:
                return 0.0
            return float(budget.in_use) / cap
        except Exception:  # noqa: BLE001 — a foreign budget shape
            return 0.0

    def _raise_one(self, thr: float, device_pressure: bool = False) -> None:
        """Additive increase: probe ONE knob upward, remember the
        pre-probe rate — the next tick keeps or reverts the raise."""
        candidates: list[tuple[str, int]] = []
        if self.streams < self.max_streams:
            candidates.append(("streams", self.streams + 1))
        if self.window_bytes < self.max_window:
            candidates.append(("window_bytes",
                               min(self.window_bytes * 2, self.max_window)))
        budget = self._budget
        headroom = True
        if budget is not None:
            try:
                headroom = (budget.max_bytes - budget.in_use
                            > self.window_bytes)
            except Exception:  # noqa: BLE001 — a foreign budget shape
                headroom = True
        # never probe prefetch upward while the device plane is the
        # bottleneck — a deeper queue just converts place latency into
        # pinned host RAM
        if self.prefetch_depth < self.max_prefetch and headroom \
                and not device_pressure:
            candidates.append(("prefetch_depth", self.prefetch_depth + 1))
        if not candidates:
            return
        knob, new = candidates[self._round_robin % len(candidates)]
        self._round_robin += 1
        old = getattr(self, knob)
        self._probe = (knob, old)
        self._probe_base = thr
        self._probe_t = self._clock()
        self._decide("increase", knob, old, new, "probe")
        setattr(self, knob, new)

    def tick(self, *, thr: float | None = None,
             retry_rate: float | None = None,
             breaker_open: bool | None = None,
             budget_wait_share: float | None = None,
             place_p99: float | None = None,
             hbm_pressure: float | None = None) -> None:
        """One control decision. Signals default to the live telemetry
        plane; tests force them via keywords."""
        tel = self._tel()
        forced = thr is not None
        if thr is None:
            thr = tel.rate("pull_bytes_total", self.window_s)
        if retry_rate is None:
            # the fault signal reads a SHORT window (judge_s, ~2 s), not
            # window_s: over a 30 s window one transient burst stays
            # above retry_hi for 30 s while the post-backoff hold is
            # only 4 ticks — the controller would re-trigger
            # multiplicative decrease ~15× off one spike and collapse
            # every knob to its floor. Current faulting, not history.
            retry_rate = tel.family_rate("peer_retries_total",
                                         self.judge_s)
        if breaker_open is None:
            breaker_open = self._breaker_open()
        if budget_wait_share is None:
            budget_wait_share = tel.rate(_BUDGET_WAIT, self.window_s)
        if place_p99 is None:
            # device-side latency: whichever of the two device-plane
            # stages is slower over the window is the pressure signal
            place_p99 = max(
                tel.window_quantile(_PLACE, 0.99, self.window_s),
                tel.window_quantile(_SINK_DELIVER, 0.99, self.window_s))
        if hbm_pressure is None:
            hbm_pressure = self._budget_pressure()
        # the p99 the ROADMAP item names: read every tick so the signal
        # is on the tuner's span when a decision fires
        p99 = tel.window_quantile(_WINDOW_READ, 0.99, self.window_s)
        metrics.HUB.set_gauge("tuner_window_read_p99", p99)
        metrics.HUB.set_gauge("tuner_place_p99", round(place_p99, 6))
        metrics.HUB.set_gauge("tuner_hbm_pressure", round(hbm_pressure, 4))
        try:
            now = self._clock()
            # every knob/bookkeeping WRITE below happens under the knob
            # lock so snapshot() reads one consistent decision state
            with self._knob_lock:
                if retry_rate > self.retry_hi or breaker_open:
                    if now >= self._hold_until:
                        self._backoff("breaker-open" if breaker_open
                                      else f"retry-rate {retry_rate:.2f}/s")
                    return
                if now < self._hold_until:
                    return
                if self._probe is not None:
                    knob, old = self._probe
                    if forced:
                        # the test seams define the post-probe rate directly
                        post = thr
                    elif now - self._probe_t >= self.judge_s:
                        # judge over ONLY the post-raise interval — the
                        # window_s moving average barely moves per tick and
                        # would rubber-stamp every probe
                        post = tel.rate("pull_bytes_total",
                                        max(now - self._probe_t, 1e-9))
                    else:
                        return  # let the raise settle before judging
                    self._probe = None
                    if self._probe_base > 0 \
                            and post < 0.85 * self._probe_base:
                        # the raise cost throughput: revert and hold
                        cur = getattr(self, knob)
                        self._decide(
                            "revert", knob, cur, old,
                            f"thr {post:.0f} < 0.85x {self._probe_base:.0f}")
                        setattr(self, knob, old)
                        self._hold_until = now + 4 * self.tick_s
                        return
                self._best_thr = max(self._best_thr, thr)
                device_pressure = (place_p99 > self.place_hi
                                   or hbm_pressure > self.hbm_hi)
                if device_pressure and \
                        self.prefetch_depth > max(1, self.min_prefetch):
                    # device-bound: the accelerator (or the landing
                    # budget feeding it) can't absorb what prefetch
                    # already committed — trade depth for place latency
                    new = self.prefetch_depth - 1
                    reason = (f"place-p99 {place_p99:.2f}s"
                              if place_p99 > self.place_hi
                              else f"hbm-pressure {hbm_pressure:.2f}")
                    self._decide("decrease", "prefetch_depth",
                                 self.prefetch_depth, new, reason)
                    self.prefetch_depth = new
                    return
                if budget_wait_share > 0.5 and \
                        self.prefetch_depth > max(1, self.min_prefetch):
                    # admission-bound: deeper prefetch pins more host RAM
                    new = self.prefetch_depth - 1
                    self._decide("decrease", "prefetch_depth",
                                 self.prefetch_depth, new,
                                 f"budget-wait share {budget_wait_share:.2f}")
                    self.prefetch_depth = new
                    return
                self._raise_one(thr, device_pressure=device_pressure)
        finally:
            # gauges reflect the POST-decision knob values — the scrape
            # and statusz must agree with what the fetch loop will use
            self._gauges(thr)


# ----------------------------------------------------- active-tuner registry
#
# statusz's effective-config section resolves tuner-overridden knobs from
# here through a sys.modules peek (a node that never tuned never imports
# this module, and a dep-light statusz scrape never allocates a tuner).

_active_lock = threading.Lock()
_active: list[PullTuner] = []


def _register(t: PullTuner) -> None:
    with _active_lock:
        _active.append(t)


def _unregister(t: PullTuner) -> None:
    with _active_lock:
        if t in _active:
            _active.remove(t)


def current() -> PullTuner | None:
    """The most recently started live tuner (None when no pull is being
    tuned) — what statusz reports knob sources from."""
    with _active_lock:
        return _active[-1] if _active else None
