"""Python wrapper over the C++ content-addressed chunk store.

Data model parity with the legacy-Rust cache (``CONTRIBUTING.md:53-154``):
bodies keyed per request URI under a 16-hex key, stored exactly as transferred
(content-encoding preserved), with a JSON ``.meta`` header sidecar. Additions:
resumable partial writes, range reads, and a running sha256 digest
(SURVEY.md §7 layer 2).
"""

from __future__ import annotations

import ctypes
import hashlib
import json
from pathlib import Path
from typing import Iterator

from demodel_tpu import native


def key_for_uri(uri: str) -> str:
    """16-hex store key: first 8 bytes of sha256(uri) — must match the C++
    ``dm::key_for_uri`` (tested in tests/test_store.py)."""
    return hashlib.sha256(uri.encode()).hexdigest()[:16]


class StoreWriter:
    def __init__(self, lib: ctypes.CDLL, handle: int):
        self._lib = lib
        self._h = handle
        self._open = True

    def append(self, data: bytes) -> None:
        rc = self._lib.dm_writer_append(self._h, data, len(data))
        if rc != 0:
            raise OSError(-rc, "store append failed")

    @property
    def offset(self) -> int:
        return self._lib.dm_writer_offset(self._h)

    def digest(self) -> str:
        buf = ctypes.create_string_buffer(65)
        self._lib.dm_writer_digest(self._h, buf)
        return buf.value.decode()

    def commit(self, meta: dict) -> None:
        rc = self._lib.dm_writer_commit(self._h, json.dumps(meta).encode())
        self._open = False
        if rc != 0:
            raise OSError(-rc, "store commit failed")

    def abort(self, keep_partial: bool = False) -> None:
        if self._open:
            self._lib.dm_writer_abort(self._h, 1 if keep_partial else 0)
            self._open = False


class RangeStoreWriter:
    """Positional writer over a preallocated partial (parallel range fetch).

    Threads call :meth:`pwrite` on disjoint ranges; :meth:`commit` verifies
    full coverage, hashes the assembled file in one pass, optionally checks
    an expected digest, and publishes atomically."""

    def __init__(self, lib: ctypes.CDLL, handle: int):
        self._lib = lib
        self._h = handle
        self._open = True

    def pwrite(self, data, offset: int) -> None:
        if isinstance(data, bytes):
            rc = self._lib.dm_rw_pwrite(self._h, data, len(data), offset)
        else:
            # numpy landing buffers pass their pointer — no bounce copy of
            # a multi-GB shard just to satisfy ctypes
            view = memoryview(data).cast("B")
            rc = self._lib.dm_rw_pwrite(
                self._h,
                (ctypes.c_char * len(view)).from_buffer(view), len(view), offset,
            )
        if rc != 0:
            raise OSError(-rc, "range write failed")

    @property
    def written(self) -> int:
        return self._lib.dm_rw_written(self._h)

    def commit(self, meta: dict, expected_digest: str | None = None) -> str:
        out = ctypes.create_string_buffer(65)
        rc = self._lib.dm_rw_commit(self._h, json.dumps(meta).encode(),
                                    (expected_digest or "").encode(), out)
        self._open = False
        if rc != 0:
            raise OSError(-rc, "ranged commit failed")
        return out.value.decode()

    def abort(self, keep_partial: bool = False) -> None:
        if self._open:
            self._lib.dm_rw_abort(self._h, 1 if keep_partial else 0)
            self._open = False


class Store:
    """Content-addressed store rooted at ``root`` (``objects/`` + ``partial/``
    + ``digests/`` content-address hardlinks)."""

    def __init__(self, root: Path | str):
        self.root = Path(root)
        self.root.parent.mkdir(parents=True, exist_ok=True)
        self._lib = native.lib()
        err = ctypes.create_string_buffer(512)
        self._h = self._lib.dm_store_open(str(self.root).encode(), err, 512)
        if not self._h:
            raise OSError(f"store open failed: {err.value.decode()}")

    def close(self) -> None:
        if self._h:
            self._lib.dm_store_close(self._h)
            self._h = None

    # -- queries ---------------------------------------------------------
    def has(self, key: str) -> bool:
        return bool(self._lib.dm_store_has(self._h, key.encode()))

    def size(self, key: str) -> int:
        return self._lib.dm_store_size(self._h, key.encode())

    def partial_size(self, key: str) -> int:
        return self._lib.dm_store_partial_size(self._h, key.encode())

    def meta(self, key: str) -> dict | None:
        n = self._lib.dm_store_meta(self._h, key.encode(), None, 0)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(n + 1)
        self._lib.dm_store_meta(self._h, key.encode(), buf, n + 1)
        try:
            return json.loads(buf.value.decode())
        except ValueError:
            return None

    def has_digest(self, digest: str) -> bool:
        return bool(self._lib.dm_store_has_digest(self._h, digest.encode()))

    def list(self) -> list[str]:
        n = self._lib.dm_store_list(self._h, None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        self._lib.dm_store_list(self._h, buf, n + 1)
        return [k for k in buf.value.decode().split("\n") if k]

    def index(self) -> dict:
        """The /peer/index JSON (public objects only) — what the native
        proxy serves; exposed for tests and the restore control plane."""
        n = self._lib.dm_store_index_json(self._h, None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        self._lib.dm_store_index_json(self._h, buf, n + 1)
        return json.loads(buf.value.decode())

    # -- reads -----------------------------------------------------------
    def pread(self, key: str, length: int, offset: int) -> bytes:
        buf = ctypes.create_string_buffer(length)
        n = self._lib.dm_store_pread(self._h, key.encode(), buf, length, offset)
        if n < 0:
            raise OSError(-n, f"pread {key} failed")
        return buf.raw[:n]

    def pread_into(self, key: str, out, offset: int = 0) -> int:
        """Range-read straight into a writable buffer (numpy uint8 view) —
        the zero-extra-copy landing path for the HBM sink."""
        view = memoryview(out).cast("B")
        n = self._lib.dm_store_pread(
            self._h, key.encode(),
            (ctypes.c_char * len(view)).from_buffer(view), len(view), offset,
        )
        if n < 0:
            raise OSError(-n, f"pread_into {key} failed")
        return n

    def get(self, key: str) -> bytes:
        size = self.size(key)
        if size < 0:
            raise KeyError(key)
        return self.pread(key, size, 0)

    def stream(self, key: str, chunk: int = 1 << 20) -> Iterator[bytes]:
        size = self.size(key)
        if size < 0:
            raise KeyError(key)
        off = 0
        while off < size:
            part = self.pread(key, min(chunk, size - off), off)
            if not part:
                break
            yield part
            off += len(part)

    # -- writes ----------------------------------------------------------
    def begin(self, key: str, resume: bool = False) -> StoreWriter:
        err = ctypes.create_string_buffer(256)
        h = self._lib.dm_store_begin(self._h, key.encode(),
                                     1 if resume else 0, err, 256)
        if not h:
            raise OSError(f"begin {key}: {err.value.decode()}")
        return StoreWriter(self._lib, h)

    def begin_ranged(self, key: str, total: int) -> RangeStoreWriter:
        err = ctypes.create_string_buffer(256)
        h = self._lib.dm_store_begin_ranged(self._h, key.encode(), total,
                                            err, 256)
        if not h:
            raise OSError(f"begin_ranged {key}: {err.value.decode()}")
        return RangeStoreWriter(self._lib, h)

    def put(self, key: str, body: bytes, meta: dict | None = None) -> str:
        digest = ctypes.create_string_buffer(65)
        rc = self._lib.dm_store_put(self._h, key.encode(), body, len(body),
                                    json.dumps(meta or {}).encode(), digest)
        if rc != 0:
            raise OSError(-rc, f"put {key} failed")
        return digest.value.decode()

    def remove(self, key: str) -> None:
        rc = self._lib.dm_store_remove(self._h, key.encode())
        if rc != 0:
            raise OSError(-rc, f"remove {key} failed")

    def gc(self, max_bytes: int) -> tuple[int, int, int]:
        """Size-capped LRU eviction over committed objects (neither
        reference generation had one — SURVEY.md §2; VERDICT r2 missing
        #5). Returns ``(total_bytes_after, freed_bytes, evicted_count)``.
        Active writers and partials are never touched."""
        freed = ctypes.c_int64(0)
        count = ctypes.c_int(0)
        total = self._lib.dm_store_gc(self._h, max_bytes,
                                      ctypes.byref(freed), ctypes.byref(count))
        if total < 0:
            raise OSError(-total, "store gc failed")
        if count.value:
            from demodel_tpu.utils import metrics as _m

            _m.HUB.inc("store_evictions_total", count.value)
            _m.HUB.inc("store_evicted_bytes_total", freed.value)
        return total, freed.value, count.value

    def evictions_total(self) -> int:
        return self._lib.dm_store_evictions(self._h)

    def is_private(self, key: str) -> bool:
        """True when the entry is auth-scoped (cached for a credentialed
        request): never advertised on /peer, refused by the peer object
        server — same rule the native plane applies (store.cc
        meta_is_private)."""
        meta = self.meta(key) or {}
        return bool(meta.get("auth_scope"))

    def pin(self, key: str) -> None:
        """Shield ``key`` from :meth:`gc` eviction (process-local). The
        restore registry pins every blob it advertises — evicting one
        mid-serve would 404 the restore data plane (ADVICE r3 medium)."""
        self._lib.dm_store_pin(self._h, key.encode())

    def unpin(self, key: str) -> None:
        self._lib.dm_store_unpin(self._h, key.encode())

    def materialize(self, key: str, digest: str, meta: dict) -> None:
        """Publish already-stored bytes (located by content digest) under a
        new key via hardlink — content-address dedup, zero copy."""
        rc = self._lib.dm_store_materialize(self._h, key.encode(),
                                            digest.encode(),
                                            json.dumps(meta).encode())
        if rc != 0:
            raise OSError(-rc, f"materialize {key} from {digest[:12]} failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
