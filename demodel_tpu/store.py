"""Python wrapper over the C++ content-addressed chunk store.

Data model parity with the legacy-Rust cache (``CONTRIBUTING.md:53-154``):
bodies keyed per request URI under a 16-hex key, stored exactly as transferred
(content-encoding preserved), with a JSON ``.meta`` header sidecar. Additions:
resumable partial writes, range reads, and a running sha256 digest
(SURVEY.md §7 layer 2).
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import os
from pathlib import Path
from typing import Iterator

from demodel_tpu import native


def key_for_uri(uri: str) -> str:
    """16-hex store key: first 8 bytes of sha256(uri) — must match the C++
    ``dm::key_for_uri`` (tested in tests/test_store.py)."""
    return hashlib.sha256(uri.encode()).hexdigest()[:16]


# Test-only disk fault hook (tests/chaosdisk.py): when installed, store
# mutations and reads consult it before touching the native layer; the hook
# either returns (no fault) or raises OSError(ENOSPC/EIO/...). Production
# never installs one, so the cost is a single module-attribute load. The
# native selftest binaries carry the equivalent twin behind
# -DDM_STORE_FAULT_INJECT, programmed via DEMODEL_STORE_FAULT.
_fault_hook = None


def set_fault_hook(hook) -> None:
    """Install (or with ``None`` clear) the test-only disk fault hook."""
    global _fault_hook
    _fault_hook = hook


def _fault(op: str, key: str, **info) -> None:
    if _fault_hook is not None:
        _fault_hook(op, key, **info)


class StoreWriter:
    def __init__(self, lib: ctypes.CDLL, handle: int,
                 store: "Store | None" = None, key: str | None = None):
        self._lib = lib
        self._h = handle
        self._open = True
        self._store = store
        self._key = key

    def append(self, data: bytes) -> None:
        if self._key is not None:
            _fault("append", self._key, offset=self.offset, length=len(data))
        rc = self._lib.dm_writer_append(self._h, data, len(data))
        if rc != 0:
            raise OSError(-rc, "store append failed")

    @property
    def offset(self) -> int:
        return self._lib.dm_writer_offset(self._h)

    def digest(self) -> str:
        buf = ctypes.create_string_buffer(65)
        self._lib.dm_writer_digest(self._h, buf)
        return buf.value.decode()

    def commit(self, meta: dict) -> None:
        if self._key is not None:
            _fault("commit", self._key, offset=self.offset)
        rc = self._lib.dm_writer_commit(self._h, json.dumps(meta).encode())
        self._open = False
        if rc != 0:
            raise OSError(-rc, "store commit failed")

    def abort(self, keep_partial: bool = False) -> None:
        if self._open:
            self._lib.dm_writer_abort(self._h, 1 if keep_partial else 0)
            self._open = False

    def checkpoint(self) -> None:
        """Durable resume point for cross-incarnation resume: fsync the
        partial, then atomically publish a ``partial/<key>.progress``
        sidecar carrying the landed watermark. After a crash,
        :meth:`Store.recover` truncates the partial to this offset (bytes
        past it may be torn) and the tier re-offers it to single-flight as
        a resume offset — the landed prefix never re-crosses the wire."""
        if self._store is None or self._key is None or not self._open:
            return
        part = self._store.root / "partial" / self._key
        try:
            fd = os.open(part, os.O_WRONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        # "offset" is deliberately a JSON *string*: the native recover
        # sweep parses the sidecar with the same string-field scanner it
        # uses for .meta, and must agree on the watermark
        doc = {"offset": str(self.offset), "sha256": self.digest()}
        tmp = part.with_name(part.name + ".progress.tmp")
        tmp.write_text(json.dumps(doc))
        os.replace(tmp, part.with_name(part.name + ".progress"))


class RangeStoreWriter:
    """Positional writer over a preallocated partial (parallel range fetch).

    Threads call :meth:`pwrite` on disjoint ranges; :meth:`commit` verifies
    full coverage, hashes the assembled file in one pass, optionally checks
    an expected digest, and publishes atomically."""

    def __init__(self, lib: ctypes.CDLL, handle: int):
        self._lib = lib
        self._h = handle
        self._open = True

    def pwrite(self, data, offset: int) -> None:
        if isinstance(data, bytes):
            rc = self._lib.dm_rw_pwrite(self._h, data, len(data), offset)
        else:
            # numpy landing buffers pass their pointer — no bounce copy of
            # a multi-GB shard just to satisfy ctypes
            view = memoryview(data).cast("B")
            rc = self._lib.dm_rw_pwrite(
                self._h,
                (ctypes.c_char * len(view)).from_buffer(view), len(view), offset,
            )
        if rc != 0:
            raise OSError(-rc, "range write failed")

    @property
    def written(self) -> int:
        return self._lib.dm_rw_written(self._h)

    def commit(self, meta: dict, expected_digest: str | None = None) -> str:
        out = ctypes.create_string_buffer(65)
        rc = self._lib.dm_rw_commit(self._h, json.dumps(meta).encode(),
                                    (expected_digest or "").encode(), out)
        self._open = False
        if rc != 0:
            raise OSError(-rc, "ranged commit failed")
        return out.value.decode()

    def abort(self, keep_partial: bool = False) -> None:
        if self._open:
            self._lib.dm_rw_abort(self._h, 1 if keep_partial else 0)
            self._open = False


class Store:
    """Content-addressed store rooted at ``root`` (``objects/`` + ``partial/``
    + ``digests/`` content-address hardlinks)."""

    def __init__(self, root: Path | str):
        self.root = Path(root)
        self.root.parent.mkdir(parents=True, exist_ok=True)
        self._lib = native.lib()
        err = ctypes.create_string_buffer(512)
        self._h = self._lib.dm_store_open(str(self.root).encode(), err, 512)
        if not self._h:
            raise OSError(f"store open failed: {err.value.decode()}")

    def close(self) -> None:
        if self._h:
            self._lib.dm_store_close(self._h)
            self._h = None

    # -- queries ---------------------------------------------------------
    def has(self, key: str) -> bool:
        return bool(self._lib.dm_store_has(self._h, key.encode()))

    def size(self, key: str) -> int:
        return self._lib.dm_store_size(self._h, key.encode())

    def partial_size(self, key: str) -> int:
        return self._lib.dm_store_partial_size(self._h, key.encode())

    def meta(self, key: str) -> dict | None:
        n = self._lib.dm_store_meta(self._h, key.encode(), None, 0)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(n + 1)
        self._lib.dm_store_meta(self._h, key.encode(), buf, n + 1)
        try:
            return json.loads(buf.value.decode())
        except ValueError:
            return None

    def has_digest(self, digest: str) -> bool:
        return bool(self._lib.dm_store_has_digest(self._h, digest.encode()))

    def list(self) -> list[str]:
        n = self._lib.dm_store_list(self._h, None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        self._lib.dm_store_list(self._h, buf, n + 1)
        return [k for k in buf.value.decode().split("\n") if k]

    def index(self) -> dict:
        """The /peer/index JSON (public objects only) — what the native
        proxy serves; exposed for tests and the restore control plane."""
        n = self._lib.dm_store_index_json(self._h, None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        self._lib.dm_store_index_json(self._h, buf, n + 1)
        return json.loads(buf.value.decode())

    # -- reads -----------------------------------------------------------
    def pread(self, key: str, length: int, offset: int) -> bytes:
        _fault("pread", key, length=length, offset=offset)
        buf = ctypes.create_string_buffer(length)
        n = self._lib.dm_store_pread(self._h, key.encode(), buf, length, offset)
        if n < 0:
            raise OSError(-n, f"pread {key} failed")
        return buf.raw[:n]

    def pread_into(self, key: str, out, offset: int = 0) -> int:
        """Range-read straight into a writable buffer (numpy uint8 view) —
        the zero-extra-copy landing path for the HBM sink."""
        view = memoryview(out).cast("B")
        n = self._lib.dm_store_pread(
            self._h, key.encode(),
            (ctypes.c_char * len(view)).from_buffer(view), len(view), offset,
        )
        if n < 0:
            raise OSError(-n, f"pread_into {key} failed")
        return n

    def get(self, key: str) -> bytes:
        size = self.size(key)
        if size < 0:
            raise KeyError(key)
        return self.pread(key, size, 0)

    def stream(self, key: str, chunk: int = 1 << 20) -> Iterator[bytes]:
        size = self.size(key)
        if size < 0:
            raise KeyError(key)
        off = 0
        while off < size:
            part = self.pread(key, min(chunk, size - off), off)
            if not part:
                break
            yield part
            off += len(part)

    # -- writes ----------------------------------------------------------
    def begin(self, key: str, resume: bool = False) -> StoreWriter:
        err = ctypes.create_string_buffer(256)
        h = self._lib.dm_store_begin(self._h, key.encode(),
                                     1 if resume else 0, err, 256)
        if not h:
            raise OSError(f"begin {key}: {err.value.decode()}")
        return StoreWriter(self._lib, h, store=self, key=key)

    def begin_ranged(self, key: str, total: int) -> RangeStoreWriter:
        err = ctypes.create_string_buffer(256)
        h = self._lib.dm_store_begin_ranged(self._h, key.encode(), total,
                                            err, 256)
        if not h:
            raise OSError(f"begin_ranged {key}: {err.value.decode()}")
        return RangeStoreWriter(self._lib, h)

    def put(self, key: str, body: bytes, meta: dict | None = None) -> str:
        digest = ctypes.create_string_buffer(65)
        rc = self._lib.dm_store_put(self._h, key.encode(), body, len(body),
                                    json.dumps(meta or {}).encode(), digest)
        if rc != 0:
            raise OSError(-rc, f"put {key} failed")
        return digest.value.decode()

    def remove(self, key: str) -> None:
        rc = self._lib.dm_store_remove(self._h, key.encode())
        if rc != 0:
            raise OSError(-rc, f"remove {key} failed")

    def gc(self, max_bytes: int) -> tuple[int, int, int]:
        """Size-capped LRU eviction over committed objects (neither
        reference generation had one — SURVEY.md §2; VERDICT r2 missing
        #5). Returns ``(total_bytes_after, freed_bytes, evicted_count)``.
        Active writers and partials are never touched."""
        freed = ctypes.c_int64(0)
        count = ctypes.c_int(0)
        total = self._lib.dm_store_gc(self._h, max_bytes,
                                      ctypes.byref(freed), ctypes.byref(count))
        if total < 0:
            raise OSError(-total, "store gc failed")
        if count.value:
            from demodel_tpu.utils import metrics as _m

            _m.HUB.inc("store_evictions_total", count.value)
            _m.HUB.inc("store_evicted_bytes_total", freed.value)
        return total, freed.value, count.value

    def evictions_total(self) -> int:
        return self._lib.dm_store_evictions(self._h)

    def is_private(self, key: str) -> bool:
        """True when the entry is auth-scoped (cached for a credentialed
        request): never advertised on /peer, refused by the peer object
        server — same rule the native plane applies (store.cc
        meta_is_private)."""
        meta = self.meta(key) or {}
        return bool(meta.get("auth_scope"))

    # -- storage-fault plane ---------------------------------------------
    def recover(self, grace_secs: float = 60.0) -> tuple[int, int]:
        """Crash-recovery sweep over ``partial/`` (native ``Store::recover``;
        already run once at open with a 60 s grace). Partials older than the
        grace carrying a ``.progress`` sidecar are truncated to their durable
        watermark and kept as resume offers; sidecar-less stale partials,
        orphan sidecars and stale tmp files are purged. Returns
        ``(resumed, purged)``."""
        resumed = ctypes.c_int(0)
        purged = ctypes.c_int(0)
        self._lib.dm_store_recover(self._h, float(grace_secs),
                                   ctypes.byref(resumed), ctypes.byref(purged))
        return resumed.value, purged.value

    def quarantine(self, key: str) -> bool:
        """Move a committed object out of the addressable namespace into
        ``quarantine/`` (EIO or digest mismatch on read), invalidating the
        hot tier, fd cache and index — the next request is a clean miss.
        Returns True when the object was quarantined."""
        rc = self._lib.dm_store_quarantine(self._h, key.encode())
        if rc == 0:
            from demodel_tpu.utils import metrics as _m

            _m.HUB.inc("store_quarantined_total")
        return rc == 0

    def probe_writable(self) -> bool:
        """One small real write+fsync through the store's write path —
        the degraded-mode exit probe (test fault hooks are honored, so an
        injected full disk keeps the node degraded)."""
        probe_key = "probe-degraded._demodel"
        try:
            _fault("probe", probe_key)
            self.put(probe_key, b"ok", {"kind": "probe", "auth_scope": "probe"})
        except OSError:
            return False
        try:
            self.remove(probe_key)
        except OSError:
            pass
        return True

    def scrub(self, max_bytes: int) -> tuple[bool, int, int, int]:
        """One bounded background-scrubber slice: re-digest up to
        ``max_bytes`` of committed objects from the saved cursor,
        quarantining any object whose bytes no longer hash to the recorded
        sha256. Returns ``(wrapped, objects, bytes, mismatched)``;
        ``wrapped`` is True when the pass completed a full walk."""
        objs = ctypes.c_int64(0)
        nbytes = ctypes.c_int64(0)
        mism = ctypes.c_int(0)
        rc = self._lib.dm_store_scrub(self._h, max_bytes, ctypes.byref(objs),
                                      ctypes.byref(nbytes), ctypes.byref(mism))
        return bool(rc), objs.value, nbytes.value, mism.value

    def storage_stats(self) -> dict:
        out = (ctypes.c_int64 * 4)()
        self._lib.dm_store_storage_stats(self._h, out)
        return {
            "quarantined_total": out[0],
            "scrub_objects_total": out[1],
            "scrub_bytes_total": out[2],
            "scrub_mismatch_total": out[3],
        }

    def pin(self, key: str) -> None:
        """Shield ``key`` from :meth:`gc` eviction (process-local). The
        restore registry pins every blob it advertises — evicting one
        mid-serve would 404 the restore data plane (ADVICE r3 medium)."""
        self._lib.dm_store_pin(self._h, key.encode())

    def unpin(self, key: str) -> None:
        self._lib.dm_store_unpin(self._h, key.encode())

    def materialize(self, key: str, digest: str, meta: dict) -> None:
        """Publish already-stored bytes (located by content digest) under a
        new key via hardlink — content-address dedup, zero copy."""
        rc = self._lib.dm_store_materialize(self._h, key.encode(),
                                            digest.encode(),
                                            json.dumps(meta).encode())
        if rc != 0:
            raise OSError(-rc, f"materialize {key} from {digest[:12]} failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
