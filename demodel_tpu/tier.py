"""Unified tiered store: host-RAM mmap hot tier ← disk ← peers ← origin.

One tier API over the content-addressed :class:`~demodel_tpu.store.Store`
(ROADMAP item 2): byte-budgeted LRU per tier, digest-verified promotion,
and **single-flight admission** at every miss edge — a cold key requested
by N concurrent callers costs exactly one upstream fetch, with every
waiter served *off the landing stream* via progress-watermark reads
against the store's resumable partials (the Python twin of the native
proxy's ``FillState`` attach), not fetch-completion barriers. A leader
that dies mid-stream elects the next waiter — which resumes the partial
with a ranged fetch — instead of failing the cohort; a digest mismatch
fails the cohort WITHOUT poisoning the key (the next request starts a
fresh flight).

Tiers and their budgets:

- **ram** — committed store objects mmap'd into host RAM, LRU under
  ``DEMODEL_TIER_RAM_MB``. The swarm plane's chunk boards charge the
  SAME budget (a host mid-swarm-pull holds chunk bytes in RAM that the
  hot tier must make room for — swarm-aware eviction).
- **disk** — the store itself under ``DEMODEL_CACHE_MAX_GB``, evicted
  through :meth:`Store.gc` (pin shield and ``store_evictions_total``
  semantics unchanged).

Dep-light by design (stdlib + the native store wrapper; no jax): the
restore server, the proxy launcher, and statusz all touch this module on
nodes that must never pay a jax import. statusz reads
:func:`tiers_snapshot` via its usual ``sys.modules`` peek.
"""

from __future__ import annotations

import errno
import hashlib
import mmap
import os
import threading
import time
import weakref
from typing import Any, Callable, Iterable

from demodel_tpu.store import Store
from demodel_tpu.utils import trace
from demodel_tpu.utils.env import (cache_max_gb, default_tier_ram_mb,
                                   store_reprobe_secs)
from demodel_tpu.utils.faults import DigestMismatch
from demodel_tpu.utils.logging import get_logger
from demodel_tpu.utils.metrics import HUB, labeled

log = get_logger("tier")

#: pre-register the tier/single-flight counter families at import so a
#: scrape types them (``# TYPE … counter``) before the first event
HUB.inc(labeled("store_tier_hits_total", tier="ram"), 0)
HUB.inc(labeled("store_tier_hits_total", tier="disk"), 0)
HUB.inc(labeled("store_tier_misses_total", tier="ram"), 0)
HUB.inc(labeled("store_tier_misses_total", tier="disk"), 0)
HUB.inc(labeled("store_tier_promotions_total", tier="ram"), 0)
HUB.inc(labeled("store_tier_evicted_bytes_total", tier="ram"), 0)
HUB.inc("singleflight_leaders_total", 0)
HUB.inc("singleflight_waiters_total", 0)
HUB.inc("singleflight_handoffs_total", 0)
#: storage-fault plane families (ISSUE 19): quarantines are counted by
#: Store.quarantine; degraded transitions and the 0/1 mode gauge live here
HUB.inc("store_quarantined_total", 0)
HUB.inc("store_degraded_entries_total", 0)
HUB.set_gauge("store_degraded", 0)

#: leader checkpoint cadence: every this-many landed bytes the partial is
#: fsync'd and the .progress watermark sidecar rewritten, bounding what a
#: kill -9 can force the next incarnation to refetch
_CHECKPOINT_BYTES = 8 << 20


def _tick(name: str, tier: str | None = None, n: int = 1) -> None:
    # demodel: allow(metric-hygiene) — forwarding helper: every caller
    # passes a literal family name, all pre-registered above
    HUB.inc(labeled(name, tier=tier) if tier else name, n)


class TierBudget:
    """Byte accounting for one tier (NOT a blocking semaphore — the
    :class:`~demodel_tpu.sink.streaming.ByteBudget` blocks producers; a
    tier budget instead drives eviction: charge unconditionally, then the
    owner evicts LRU entries until :meth:`over` is zero)."""

    def __init__(self, name: str, max_bytes: int):
        self.name = name
        self.max_bytes = int(max_bytes)
        self._in_use = 0
        self.high_water = 0
        self._lock = threading.Lock()

    @property
    def in_use(self) -> int:
        with self._lock:
            return self._in_use

    def charge(self, nbytes: int) -> None:
        with self._lock:
            self._in_use += int(nbytes)
            if self._in_use > self.high_water:
                self.high_water = self._in_use

    def release(self, nbytes: int) -> None:
        with self._lock:
            self._in_use -= int(nbytes)

    def over(self) -> int:
        """Bytes past the budget (0 when inside it, or unbounded)."""
        with self._lock:
            if self.max_bytes <= 0:
                return 0
            return max(0, self._in_use - self.max_bytes)

    def describe(self) -> dict[str, Any]:
        with self._lock:
            return {"name": self.name, "max_bytes": self.max_bytes,
                    "in_use_bytes": self._in_use,
                    "high_water_bytes": self.high_water}


#: process-wide host-RAM tier budget — the hot tier AND the swarm chunk
#: boards charge here, so a mid-pull host sheds mmap'd hot objects to
#: make room for landing chunks instead of overshooting host RAM
_ram_budget: TierBudget | None = None
_ram_budget_lock = threading.Lock()


def ram_budget() -> TierBudget:
    global _ram_budget
    with _ram_budget_lock:
        if _ram_budget is None:
            _ram_budget = TierBudget("tier-ram",
                                     default_tier_ram_mb() << 20)
        return _ram_budget


class _HotObj:
    __slots__ = ("mm", "size", "digest", "last_use")

    def __init__(self, mm: mmap.mmap, size: int, digest: str):
        self.mm = mm
        self.size = size
        self.digest = digest
        self.last_use = time.monotonic()


class HotTier:
    """mmap-backed host-RAM tier over COMMITTED store objects.

    Promotion maps ``objects/<key>`` read-only, hashes the mapped bytes,
    and verifies them against the store's content-address record (the
    ``digests/<sha256>`` hardlink must point at the same inode) — bytes
    that no longer match their digest are refused, never served.
    Demotion is a drop: the disk copy is canonical (verified at commit),
    so eviction releases the mapping and the budget charge.

    Reads return ``bytes`` copies taken under the lock — no exported
    memoryview can outlive an eviction's ``mmap.close()``.
    """

    def __init__(self, store: Store, budget: TierBudget | None = None):
        self.store = store
        self.budget = budget if budget is not None else ram_budget()
        self._objs: dict[str, _HotObj] = {}
        self._lock = threading.Lock()

    # -- reads -----------------------------------------------------------
    def read(self, key: str, offset: int = 0,
             length: int | None = None) -> bytes | None:
        with self._lock:
            obj = self._objs.get(key)
            if obj is None:
                return None
            obj.last_use = time.monotonic()
            end = obj.size if length is None else min(obj.size,
                                                      offset + length)
            _tick("store_tier_hits_total", "ram")
            return bytes(obj.mm[offset:end])

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._objs

    # -- promotion / demotion -------------------------------------------
    def promote(self, key: str) -> bool:
        """disk → RAM, digest-verified. False when the object is absent,
        larger than the whole budget, or fails verification."""
        with self._lock:
            if key in self._objs:
                return True
        size = self.store.size(key)
        if size < 0:
            return False
        if self.budget.max_bytes > 0 and size > self.budget.max_bytes:
            return False  # would evict the entire tier for one object
        path = os.path.join(str(self.store.root), "objects", key)
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return False
        mm = None
        try:
            try:
                if size == 0:
                    return False  # nothing to map; zero-byte hits stay
                    # on disk
                mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
            except (OSError, ValueError):
                return False
            finally:
                os.close(fd)
            digest = hashlib.sha256(mm).hexdigest()
            if not self._digest_matches(key, path, digest):
                mm.close()
                log.warning("hot-tier promotion refused: %s fails digest "
                            "verification — quarantining", key)
                # bit-rot caught on the read path: move the object out of
                # the addressable namespace so the next request re-fetches
                # instead of re-verifying the same corrupt bytes forever
                self.store.quarantine(key)
                return False
            with self._lock:
                if key in self._objs:  # lost a promote race; keep the
                    mm.close()         # first mapping
                    return True
                self._objs[key] = _HotObj(mm, size, digest)
        except BaseException:
            # the mapping is this frame's obligation until it is stored:
            # a raise in close/hashing/verification must not strand a
            # PROT_READ mapping of the whole object
            if mm is not None:
                mm.close()
            raise
        self.budget.charge(size)
        _tick("store_tier_promotions_total", "ram")
        self.trim()
        return True

    def _digest_matches(self, key: str, obj_path: str, digest: str) -> bool:
        """The computed hash must be the store's content-address for this
        exact inode (``digests/<digest>`` hardlinked to ``objects/<key>``),
        or match the digest the commit recorded in the meta sidecar
        (private objects have no digest link). A computed hash that finds
        neither while the inode has extra hardlinks means the bytes
        diverged from their recorded address — only ``digests/`` ever
        hardlinks objects, so ``st_nlink >= 2`` proves a link exists
        under some OTHER hash. Objects with no recorded digest anywhere
        (hand-materialized fixtures) are accepted on the computed hash
        alone — there is nothing on record to disagree with."""
        link = os.path.join(str(self.store.root), "digests", digest)
        try:
            if os.stat(link).st_ino == os.stat(obj_path).st_ino:
                return True
        except OSError:
            pass
        meta = self.store.meta(key) or {}
        recorded = meta.get("sha256") or meta.get("digest")
        if recorded:
            return recorded == digest
        try:
            if os.stat(obj_path).st_nlink >= 2:
                return False  # content-addressed under a different hash
        except OSError:
            return False
        return True

    def invalidate(self, key: str) -> None:
        """Drop a key (store remove / re-put made the mapping stale)."""
        with self._lock:
            obj = self._objs.pop(key, None)
        if obj is not None:
            self._drop(obj)

    def _drop(self, obj: _HotObj) -> None:
        self.budget.release(obj.size)
        _tick("store_tier_evicted_bytes_total", "ram", obj.size)
        try:
            obj.mm.close()
        except BufferError:  # pragma: no cover — reads copy under the
            pass             # lock, so no exported view should be live

    def trim(self) -> int:
        """LRU-evict until the shared RAM budget is met (swarm chunk
        boards charge the same budget, so their landings push hot
        objects out first). Returns bytes evicted."""
        evicted = 0
        while self.budget.over() > 0:
            with self._lock:
                if not self._objs:
                    break  # the overshoot is chunk-board charge, not ours
                key = min(self._objs, key=lambda k: self._objs[k].last_use)
                obj = self._objs.pop(key)
            self._drop(obj)
            evicted += obj.size
        return evicted

    def describe(self) -> dict[str, Any]:
        with self._lock:
            objs, nbytes = len(self._objs), sum(
                o.size for o in self._objs.values())
        doc = self.budget.describe()
        doc.update({"tier": "ram", "objects": objs, "bytes": nbytes})
        return doc

    def close(self) -> None:
        with self._lock:
            objs, self._objs = list(self._objs.values()), {}
        for obj in objs:
            self._drop(obj)


# ---------------------------------------------------------- single-flight


class _Flight:
    """One in-flight cohort for one key: a leader landing bytes into the
    store partial, waiters following its progress watermark."""

    def __init__(self, key: str):
        self.key = key
        self.cv = threading.Condition()
        self.watermark = 0          # bytes durably in partial/<key>
        self.done = False
        self.ok = False
        self.error: BaseException | None = None
        self.leader_needed = False  # the leader died; next waiter claims
        self.waiters = 0
        self.handoffs = 0
        #: degraded read-through relay: when the disk can't land bytes the
        #: leader accumulates the object here instead of in partial/<key>;
        #: waiters read this buffer off the watermark and the herd still
        #: collapses onto one upstream stream
        self.buf: bytearray | None = None

    # leader side ---------------------------------------------------------
    def set_watermark(self, n: int) -> None:
        with self.cv:
            self.watermark = n
            self.cv.notify_all()

    def advance(self, n: int) -> None:
        with self.cv:
            self.watermark += n
            self.cv.notify_all()

    def start_relay(self, prefix: bytes) -> None:
        """Switch the flight to in-memory relay mode (degraded
        read-through), seeding it with whatever prefix already landed."""
        with self.cv:
            self.buf = bytearray(prefix)
            self.watermark = len(self.buf)
            self.cv.notify_all()

    def relay(self, chunk: bytes) -> None:
        with self.cv:
            assert self.buf is not None
            self.buf += chunk
            self.watermark = len(self.buf)
            self.cv.notify_all()

    def finish(self, ok: bool, error: BaseException | None = None) -> None:
        with self.cv:
            self.done = True
            self.ok = ok
            self.error = error
            self.cv.notify_all()

    def resign(self, error: BaseException) -> bool:
        """Leader failure: hand the flight to a waiter if any is present
        (returns True), else fail it. The partial stays on disk either
        way — the successor (this cohort's or a future flight's) resumes
        it with a ranged fetch instead of starting over."""
        with self.cv:
            if self.waiters > 0:
                self.leader_needed = True
                self.error = error  # surfaced if no waiter can take over
                self.cv.notify_all()
                return True
            self.done = True
            self.ok = False
            self.error = error
            self.cv.notify_all()
            return False


class SingleFlight:
    """Per-key admission registry: the first caller in becomes the
    leader, everyone else a waiter. A finished flight (ok or failed)
    leaves the registry immediately, so failure never poisons the key."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._flights: dict[str, _Flight] = {}

    def lease(self, key: str) -> tuple[_Flight, bool]:
        """(flight, is_leader). Waiters are counted in under the registry
        lock so a resigning leader can never miss them."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight(key)
                self._flights[key] = flight
                return flight, True
            with flight.cv:
                flight.waiters += 1
            return flight, False

    def finish(self, key: str, flight: _Flight) -> None:
        with self._lock:
            if self._flights.get(key) is flight:
                del self._flights[key]

    def in_flight(self) -> int:
        with self._lock:
            return len(self._flights)

    def describe(self) -> list[dict[str, Any]]:
        with self._lock:
            flights = list(self._flights.items())
        out = []
        for key, f in flights:
            with f.cv:
                out.append({"key": key, "watermark": f.watermark,
                            "waiters": f.waiters,
                            "handoffs": f.handoffs,
                            "leader_needed": f.leader_needed})
        return out

    # -- generic collapse (no watermark streaming) -----------------------
    def do(self, key: str, fn: Callable[[], Any],
           timeout: float | None = None) -> Any:
        """Collapse concurrent ``fn`` calls for one key: the leader runs
        it, waiters block on the outcome; a failed leader hands the call
        to the next waiter (each retry is ``fn`` again — resumable work
        resumes itself). Used at miss edges that land bytes positionally
        (parallel ranged peer fetch) where a linear watermark does not
        exist; the result of the leader's ``fn`` is NOT shared (callers
        re-read the store), only the admission is."""
        deadline = None if timeout is None else time.monotonic() + timeout
        flight, leader = self.lease(key)
        if not leader:
            _tick("singleflight_waiters_total")
            became_leader = False
            with flight.cv:
                while not flight.done and not flight.leader_needed:
                    if not _wait(flight.cv, deadline):
                        flight.waiters -= 1
                        raise TimeoutError(
                            f"single-flight wait for {key} timed out")
                if flight.leader_needed:
                    flight.leader_needed = False
                    flight.handoffs += 1
                    became_leader = True
                flight.waiters -= 1
                if not became_leader:
                    if flight.ok:
                        return None
                    raise flight.error or OSError(
                        f"single-flight fetch of {key} failed")
            _tick("singleflight_handoffs_total")
        _tick("singleflight_leaders_total")
        try:
            result = fn()
        except BaseException as e:
            if not flight.resign(e):
                self.finish(key, flight)
            raise
        flight.finish(ok=True)
        self.finish(key, flight)
        return result


def _wait(cv: threading.Condition, deadline: float | None) -> bool:
    """One bounded cv wait; False once the deadline passed."""
    if deadline is None:
        cv.wait()
        return True
    left = deadline - time.monotonic()
    if left <= 0:
        return False
    cv.wait(min(left, 1.0))
    return True


#: default per-waiter progress deadline: no watermark movement for this
#: long means the leader is wedged beyond the wire plane's own retries
_STALL_SECS = 60.0


class TieredStore:
    """The tier API: ``read`` consults RAM → disk → (via ``fetch``)
    peers/origin, with single-flight admission on the miss edge.

    ``fetch(key, offset)`` is the caller's upstream: an iterator of byte
    chunks starting at ``offset`` (a takeover leader passes the resumed
    partial's size — upstreams honoring Range resume pay only the tail).
    """

    def __init__(self, store: Store, hot_budget: TierBudget | None = None,
                 name: str = "tier"):
        self.store = store
        self.name = name
        self.hot = HotTier(store, hot_budget)
        self.flights = SingleFlight()
        # degraded read-through mode (storage-fault plane): entered when
        # an emergency-evicted disk still refuses a landing write; misses
        # then stream upstream → caller without landing bytes until a
        # rate-limited re-probe sees the disk accept writes again
        self._degraded_lock = threading.Lock()
        self._degraded = False
        self._degraded_since = 0.0
        self._degraded_entries = 0
        self._last_probe = 0.0
        with _tier_registry_lock:
            _tier_registry.add(self)

    # -- degraded read-through mode --------------------------------------
    def degraded(self) -> bool:
        with self._degraded_lock:
            return self._degraded

    def _enter_degraded(self, err: BaseException) -> None:
        with self._degraded_lock:
            if self._degraded:
                return
            self._degraded = True
            self._degraded_since = time.time()
            self._degraded_entries += 1
        HUB.set_gauge("store_degraded", 1)
        HUB.inc("store_degraded_entries_total")
        log.warning("store write failed (%s) after emergency eviction: "
                    "entering degraded read-through mode — misses stream "
                    "uncached until the disk accepts writes again", err)

    def _maybe_exit_degraded(self) -> None:
        """Rate-limited re-probe (``DEMODEL_STORE_REPROBE_SECS``): one
        small real write through the store's write path; success exits
        degraded mode automatically."""
        with self._degraded_lock:
            if not self._degraded:
                return
            now = time.monotonic()
            if now - self._last_probe < store_reprobe_secs():
                return
            self._last_probe = now
        if self.store.probe_writable():
            with self._degraded_lock:
                self._degraded = False
                self._degraded_since = 0.0
            HUB.set_gauge("store_degraded", 0)
            log.info("store writable again: leaving degraded read-through "
                     "mode")

    # -- the read path ---------------------------------------------------
    def read(self, key: str,
             fetch: Callable[[str, int], Iterable[bytes]] | None = None,
             meta: dict | None = None,
             expected_digest: str | None = None,
             timeout: float | None = None) -> bytes:
        """Full object bytes for ``key`` from the nearest tier; a miss
        with no ``fetch`` raises ``KeyError``."""
        hot = self.hot.read(key)
        if hot is not None:
            return hot
        _tick("store_tier_misses_total", "ram")
        self._maybe_exit_degraded()
        if self.store.has(key):
            _tick("store_tier_hits_total", "disk")
            try:
                body = self.store.get(key)
            except OSError as e:
                if e.errno != errno.EIO:
                    raise
                # EIO on a committed object: the media under it is bad —
                # quarantine (hot tier + fd cache + index invalidated by
                # the store) and re-enter the miss path below
                self.hot.invalidate(key)
                self.store.quarantine(key)
                log.warning("EIO reading committed object %s: quarantined, "
                            "re-entering miss path", key)
            else:
                self.hot.promote(key)
                return body
        _tick("store_tier_misses_total", "disk")
        if fetch is None:
            raise KeyError(key)
        flight, leader = self.flights.lease(key)
        if leader:
            return self._lead(flight, fetch, meta, expected_digest)
        return self._follow(flight, fetch, meta, expected_digest, timeout)

    def _lead(self, flight: _Flight,
              fetch: Callable[[str, int], Iterable[bytes]],
              meta: dict | None, expected_digest: str | None) -> bytes:
        key = flight.key
        _tick("singleflight_leaders_total")
        if self.degraded():
            # degraded read-through: no landing write may even be tried —
            # stream upstream → cohort through the in-memory relay
            return self._lead_relay(flight, fetch, expected_digest,
                                    stream=None, prefix=b"")
        with trace.span("tier.lead", key=key):
            try:
                w = self.store.begin(key, resume=True)
            except OSError as e:
                # a non-cohort writer (direct store user) owns the
                # partial; surface as a failed flight, key unpoisoned
                self.flights.finish(key, flight)
                flight.finish(ok=False, error=e)
                raise
            relaying = False
            try:
                with flight.cv:
                    flight.buf = None  # takeover after relay: disk again
                flight.set_watermark(w.offset)
                stream = iter(fetch(key, w.offset))
                unsynced = 0
                for chunk in stream:
                    try:
                        w.append(chunk)
                    except OSError as e:
                        if e.errno != errno.ENOSPC:
                            raise
                        # full disk mid-landing: emergency eviction + ONE
                        # retry; a still-full disk flips the node into
                        # degraded read-through and the cohort keeps
                        # streaming off an in-memory relay seeded with
                        # the durably landed prefix
                        self.enforce()
                        try:
                            w.append(chunk)
                        except OSError as e2:
                            if e2.errno != errno.ENOSPC:
                                raise
                            self._enter_degraded(e2)
                            prefix = _partial_bytes(self.store, key,
                                                    w.offset)
                            w.checkpoint()
                            w.abort(keep_partial=True)
                            relaying = True
                            return self._lead_relay(
                                flight, fetch, expected_digest,
                                stream=stream, prefix=prefix + chunk)
                    flight.advance(len(chunk))
                    unsynced += len(chunk)
                    if unsynced >= _CHECKPOINT_BYTES:
                        # durable resume point: a kill -9 past here costs
                        # the next incarnation at most _CHECKPOINT_BYTES
                        # of refetch (Store.recover truncates to this)
                        w.checkpoint()
                        unsynced = 0
                digest = w.digest()
                if expected_digest and digest != expected_digest:
                    # drop the partial: the BYTES are wrong, resuming
                    # them would re-fail every successor
                    w.abort(keep_partial=False)
                    err = DigestMismatch(
                        f"{key}: got {digest[:12]}, "
                        f"want {expected_digest[:12]}")
                    self.flights.finish(key, flight)
                    flight.finish(ok=False, error=err)
                    raise err
                try:
                    w.commit(meta or {})
                except OSError as e:
                    if e.errno != errno.ENOSPC:
                        raise
                    # commit-time ENOSPC (meta sidecar): the body is fully
                    # durable in the partial — release the writer guard
                    # keeping the partial (no-op when the native commit
                    # already released it), evict, re-open (resume
                    # rehashes the partial) and publish again
                    w.abort(keep_partial=True)
                    self.enforce()
                    self.store.begin(key, resume=True).commit(meta or {})
            except DigestMismatch:
                raise
            except BaseException as e:
                if not relaying:
                    w.abort(keep_partial=True)
                    if not flight.resign(e):
                        self.flights.finish(key, flight)
                raise
            self.flights.finish(key, flight)
            flight.finish(ok=True)
            body = self.store.get(key)
            self.hot.promote(key)
            return body

    def _lead_relay(self, flight: _Flight,
                    fetch: Callable[[str, int], Iterable[bytes]],
                    expected_digest: str | None,
                    stream: "Iterable[bytes] | None",
                    prefix: bytes) -> bytes:
        """Degraded read-through leader: upstream → cohort through the
        flight's in-memory relay, landing nothing on disk. ``stream``
        continues a partially-consumed fetch iterator (the mid-stream
        ENOSPC switch); ``prefix`` is whatever had already landed."""
        key = flight.key
        with trace.span("tier.lead_degraded", key=key):
            try:
                flight.start_relay(prefix)
                if stream is None:
                    stream = iter(fetch(key, len(prefix)))
                for chunk in stream:
                    flight.relay(chunk)
                buf = bytes(flight.buf or b"")
                if expected_digest:
                    digest = hashlib.sha256(buf).hexdigest()
                    if digest != expected_digest:
                        err = DigestMismatch(
                            f"{key}: got {digest[:12]}, "
                            f"want {expected_digest[:12]} (degraded)")
                        self.flights.finish(key, flight)
                        flight.finish(ok=False, error=err)
                        raise err
            except DigestMismatch:
                raise
            except BaseException as e:
                if not flight.resign(e):
                    self.flights.finish(key, flight)
                raise
            self.flights.finish(key, flight)
            flight.finish(ok=True)
            return buf

    def _follow(self, flight: _Flight,
                fetch: Callable[[str, int], Iterable[bytes]],
                meta: dict | None, expected_digest: str | None,
                timeout: float | None) -> bytes:
        """Progress-watermark reads off the landing stream: pread the
        growing ``partial/<key>`` as the leader's watermark advances —
        the fd stays valid across the commit rename, so the tail is
        readable even after publication."""
        key = flight.key
        _tick("singleflight_waiters_total")
        stall = _STALL_SECS if timeout is None else timeout
        part_path = os.path.join(str(self.store.root), "partial", key)
        out = bytearray()
        fd = -1
        counted = True  # still in the flight's waiter count
        try:
            with trace.span("tier.follow", key=key):
                while True:
                    with flight.cv:
                        deadline = time.monotonic() + stall
                        while (flight.watermark <= len(out)
                               and not flight.done
                               and not flight.leader_needed):
                            if not _wait(flight.cv, deadline):
                                raise TimeoutError(
                                    f"no landing-stream progress on {key} "
                                    f"for {stall:.0f}s")
                        if flight.leader_needed:
                            flight.leader_needed = False
                            flight.handoffs += 1
                            flight.waiters -= 1
                            counted = False
                            takeover = True
                        else:
                            takeover = False
                            wm, done, ok = (flight.watermark, flight.done,
                                            flight.ok)
                    if takeover:
                        _tick("singleflight_handoffs_total")
                        log.info("single-flight takeover: %s at %d bytes",
                                 key, flight.watermark)
                        return self._lead(flight, fetch, meta,
                                          expected_digest)
                    if wm > len(out):
                        # degraded read-through: the leader relays through
                        # the flight buffer instead of the partial
                        with flight.cv:
                            relay = flight.buf
                            if relay is not None:
                                out += bytes(
                                    relay[len(out):min(wm, len(relay))])
                        while len(out) < wm:
                            if fd < 0:
                                fd = os.open(part_path, os.O_RDONLY)
                            chunk = os.pread(fd, wm - len(out), len(out))
                            if not chunk:
                                break  # torn rename edge: retry via store
                            out += chunk
                    if done:
                        if not ok:
                            raise flight.error or OSError(
                                f"single-flight fetch of {key} failed")
                        with flight.cv:
                            relay = flight.buf
                        if relay is not None:
                            if len(out) < len(relay):
                                out += bytes(relay[len(out):])
                            return bytes(out)
                        if len(out) < flight.watermark:
                            # never opened the partial (commit landed
                            # between waits) — read the published object
                            return self.store.get(key)
                        self.hot.promote(key)
                        return bytes(out)
        finally:
            if counted:
                with flight.cv:
                    flight.waiters -= 1
            if fd >= 0:
                os.close(fd)

    # -- eviction --------------------------------------------------------
    def enforce(self) -> None:
        """Budget-driven eviction across both tiers (replaces the old
        post-pull ``_maybe_gc`` sweep): trim the RAM tier to the shared
        budget, then the disk tier to ``DEMODEL_CACHE_MAX_GB`` via
        :meth:`Store.gc` — pins shield exactly as before, and the
        ``store_evictions_total`` counters keep their semantics."""
        self.hot.trim()
        enforce_disk_budget(self.store)

    def describe(self) -> dict[str, Any]:
        doc = {"name": self.name, "tiers": [self.hot.describe()],
               "singleflight": {
                   "in_flight": self.flights.in_flight(),
                   "flights": self.flights.describe()}}
        max_gb = cache_max_gb()
        doc["tiers"].append({"tier": "disk",
                             "max_bytes": max_gb << 30 if max_gb else 0})
        with self._degraded_lock:
            storage = {"degraded": self._degraded,
                       "degraded_since": self._degraded_since,
                       "degraded_entries": self._degraded_entries}
        storage.update(self.store.storage_stats())
        doc["storage"] = storage
        return doc

    def close(self) -> None:
        self.hot.close()


def _partial_bytes(store: Store, key: str, size: int) -> bytes:
    """The durably landed prefix of ``partial/<key>`` — the relay seed for
    a mid-stream degraded switch (waiters already streamed these bytes, so
    a short read here must fail the flight, not desync it)."""
    if size <= 0:
        return b""
    path = os.path.join(str(store.root), "partial", key)
    with open(path, "rb") as f:
        data = f.read(size)
    if len(data) != size:
        raise OSError(errno.EIO, f"partial prefix short for {key}")
    return data


def enforce_disk_budget(store: Store) -> None:
    """Disk-tier budget: ``DEMODEL_CACHE_MAX_GB`` (0 = unbounded) through
    :meth:`Store.gc` — active writers/partials untouched, pinned keys
    shielded (native gc), eviction counters unchanged."""
    max_gb = cache_max_gb()
    if max_gb > 0:
        total, freed, evicted = store.gc(max_gb << 30)
        if evicted:
            log.info("disk tier: evicted %d objects (%.1f MB); %.1f MB in "
                     "use", evicted, freed / 1e6, total / 1e6)


#: weak registry of live TieredStores — statusz iterates it (sys.modules
#: peek; a collected tier falls out on its own)
_tier_registry_lock = threading.Lock()
_tier_registry: "weakref.WeakSet[TieredStore]" = weakref.WeakSet()

#: process-shared tier per store root (the restore server and the pull
#: plane must hit ONE hot tier + ONE flight registry per store)
_shared_lock = threading.Lock()
_shared: dict[str, "weakref.ReferenceType[TieredStore]"] = {}


def shared(store: Store) -> TieredStore:
    root = str(store.root)
    with _shared_lock:
        ref = _shared.get(root)
        tier = ref() if ref is not None else None
        if tier is None:
            tier = TieredStore(store, name=f"tier:{os.path.basename(root)}")
            _shared[root] = weakref.ref(tier)
        return tier


def shed_ram() -> int:
    """Trim every live hot tier to the shared RAM budget. The swarm
    plane calls this after charging chunk-board bytes, so a landing
    chunk pushes mmap'd hot objects out instead of overshooting host
    RAM (swarm-aware eviction). Returns bytes evicted."""
    with _tier_registry_lock:
        tiers = list(_tier_registry)
    return sum(t.hot.trim() for t in tiers)


def tiers_snapshot() -> list[dict[str, Any]]:
    """Live tier state for ``/debug/statusz`` (read-only): per-tier
    occupancy/budget plus in-flight single-flight leaders."""
    with _tier_registry_lock:
        tiers = list(_tier_registry)
    out = [t.describe() for t in sorted(tiers, key=lambda t: t.name)]
    budget = _ram_budget
    if budget is not None and not out:
        # chunk boards can charge the RAM budget before any TieredStore
        # exists — the budget is still worth reporting
        out.append({"name": "ram-budget", "tiers": [budget.describe()],
                    "singleflight": {"in_flight": 0, "flights": []}})
    return out
