from demodel_tpu.utils.env import env_bool, env_int
from demodel_tpu.utils.logging import get_logger

__all__ = ["env_bool", "env_int", "get_logger"]
