"""Degrade-not-crash env parsing.

The reference's env handling panics the whole server on config mistakes
(``mo.Result.MustGet``, ``start.go:170-173``); here a malformed value logs a
warning and yields the default — a proxy node must not die because someone
fat-fingered an integer.
"""

from __future__ import annotations

import os

from demodel_tpu.utils.logging import get_logger

log = get_logger("env")

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("", "0", "false", "no", "off")


def env_int(name: str, default: int, minimum: int | None = None) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        log.warning("%s=%r is not an integer; using default %d", name, raw,
                    default)
        return default
    if minimum is not None and val < minimum:
        log.warning("%s=%d below minimum %d; clamping", name, val, minimum)
        return minimum
    return val


def env_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    low = raw.strip().lower()
    if low in _TRUE:
        return True
    if low in _FALSE:
        return False
    log.warning("%s=%r is not a boolean; using default %s", name, raw, default)
    return default


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("%s=%r is not a float; using default %s", name, raw,
                    default)
        return default


def available_cpus() -> int:
    """CPUs this process may actually run on — sched_getaffinity sees
    cgroup/affinity limits (a container pinned to 1 CPU on a 64-core
    host); cpu_count() is the fallback where affinity is unsupported.
    Concurrency defaults (peer streams, sink prefetch) clamp to this:
    extra threads/sockets only help when cores exist to drain them."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


# ---- pull/swarm-plane knob defaults ----------------------------------
#
# These resolve HERE (stdlib-only) rather than in their consuming
# modules because the statusz effective-config surface must report them
# dep-light: importing parallel.peer, parallel.placement, or sink.tuner
# runs those packages' __init__ and drags in jax — a statusz scrape must
# never do that. The consumers (peer._peer_streams, placement, tuner)
# delegate to these, so there is exactly one copy of each default.


def default_peer_streams() -> int:
    """``DEMODEL_PEER_STREAMS``: connections per large-object peer
    transfer. The unset default clamps to the core count — extra sockets
    on a 1-core host just contend (measured −18% at 1 core, 8 streams);
    an explicit env value always wins."""
    return env_int("DEMODEL_PEER_STREAMS",
                   max(1, min(8, available_cpus())), minimum=1)


def default_pull_window_mb() -> int:
    """``DEMODEL_PULL_WINDOW_MB``: fetch window granularity (default 32
    — large enough to amortize per-window overhead, small enough that
    one flaky window's retry cost stays bounded)."""
    return env_int("DEMODEL_PULL_WINDOW_MB", 32, minimum=1)


def tuner_enabled() -> bool:
    """``DEMODEL_TUNER``: the adaptive pull tuner switch — on unless
    explicitly disabled (=0 restores the fixed env defaults)."""
    return env_bool("DEMODEL_TUNER", True)


def default_swarm_chunk_mb() -> int:
    return env_int("DEMODEL_SWARM_CHUNK_MB", 8, minimum=1)


def default_swarm_fill_timeout() -> float:
    return float(env_int("DEMODEL_SWARM_FILL_TIMEOUT", 60, minimum=1))


def default_swarm_origin_streams() -> int:
    return env_int("DEMODEL_SWARM_ORIGIN_STREAMS", 1, minimum=1)


def swarm_reap_enabled() -> bool:
    """``DEMODEL_SWARM_REAP``=0 keeps the pre-reaper retain-until-
    close() board behavior (e.g. a warm standby that WANTS to keep
    serving)."""
    return env_bool("DEMODEL_SWARM_REAP", True)


def cache_max_gb() -> int:
    """``DEMODEL_CACHE_MAX_GB``: the disk tier's byte budget in GB
    (0 = unbounded). One resolver for every enforcement point — the
    native proxy's serving-loop gc, the pull plane's post-pull sweep,
    and the tier API's :func:`demodel_tpu.tier.enforce_disk_budget`."""
    return env_int("DEMODEL_CACHE_MAX_GB", 0, minimum=0)


def default_tier_ram_mb() -> int:
    """``DEMODEL_TIER_RAM_MB``: the host-RAM tier's byte budget in MB —
    mmap'd hot objects AND in-flight swarm chunk boards charge the same
    budget (chunk landings push hot objects out, never the reverse)."""
    return env_int("DEMODEL_TIER_RAM_MB", 256, minimum=1)


def telemetry_archive_dir() -> str:
    """``DEMODEL_TELEMETRY_ARCHIVE``: directory for the durable telemetry
    archive (:mod:`demodel_tpu.utils.retention`). Empty/unset disables
    the retention plane entirely — no import, no flusher thread."""
    return os.environ.get("DEMODEL_TELEMETRY_ARCHIVE", "").strip()


def telemetry_retain_mb() -> int:
    """``DEMODEL_TELEMETRY_RETAIN_MB``: byte budget for archived
    telemetry segments; oldest segments are evicted past it."""
    return env_int("DEMODEL_TELEMETRY_RETAIN_MB", 64, minimum=1)


def telemetry_retain_hours() -> int:
    """``DEMODEL_TELEMETRY_RETAIN_HOURS``: age budget for archived
    telemetry segments (default three days of history)."""
    return env_int("DEMODEL_TELEMETRY_RETAIN_HOURS", 72, minimum=1)


def profile_hz() -> int:
    """``DEMODEL_PROFILE_HZ``: sampling rate of the continuous profiler
    (default 19 — deliberately off the common 10/100 Hz beat so periodic
    work at round rates doesn't alias into or out of the profile)."""
    return env_int("DEMODEL_PROFILE_HZ", 19, minimum=1)


def profile_max_stacks() -> int:
    """``DEMODEL_PROFILE_MAX_STACKS``: bound on distinct folded stacks
    the profiler aggregates; past it new stacks fold into ``(other)`` and
    a drop counter — the aggregate must stay bounded on any workload."""
    return env_int("DEMODEL_PROFILE_MAX_STACKS", 2048, minimum=16)


def profile_window_s() -> int:
    """``DEMODEL_PROFILE_WINDOW_S``: seconds per profile window rolled
    into the telemetry archive (Python plane only — the native sampler
    exports cumulative aggregates and the restore server windows them)."""
    return env_int("DEMODEL_PROFILE_WINDOW_S", 60, minimum=5)


def proxy_write_timeout() -> int:
    """``DEMODEL_PROXY_WRITE_TIMEOUT``: per-connection deadline (seconds)
    for the reactor's EPOLLOUT writer to fully drain one response; a
    client still holding an undrained body past it is evicted."""
    return env_int("DEMODEL_PROXY_WRITE_TIMEOUT", 75, minimum=1)


def proxy_write_min_bps() -> int:
    """``DEMODEL_PROXY_WRITE_MIN_BPS``: low-watermark drain rate for the
    writer stall sweep — a connection draining slower than this (checked
    about once a second) is evicted early. 0 (the default) disables the
    watermark; only the write deadline then bounds a slow reader."""
    return env_int("DEMODEL_PROXY_WRITE_MIN_BPS", 0, minimum=0)


def proxy_ktls() -> bool:
    """``DEMODEL_PROXY_KTLS``: allow kernel-TLS ``SSL_sendfile`` for
    MITM'd cache hits (on by default; availability is runtime-probed and
    the chunked ``SSL_write`` pump is the automatic fallback)."""
    return env_bool("DEMODEL_PROXY_KTLS", True)


def gen_block_tokens() -> int:
    """``DEMODEL_GEN_BLOCK``: tokens per KV-cache block in the paged
    generation pool (:mod:`demodel_tpu.serve.kvcache`). Smaller blocks
    waste less tail capacity per sequence; larger blocks cut block-table
    overhead. 16 matches the vLLM default."""
    return env_int("DEMODEL_GEN_BLOCK", 16, minimum=1)


def gen_kv_mb() -> int:
    """``DEMODEL_GEN_KV_MB``: byte budget (MB) for the paged KV pool —
    the serving twin of ``DEMODEL_TIER_RAM_MB``, accounted through the
    same :class:`~demodel_tpu.tier.TierBudget` shape so KV memory shows
    up next to the RAM tier on statusz."""
    return env_int("DEMODEL_GEN_KV_MB", 256, minimum=1)


def gen_max_batch() -> int:
    """``DEMODEL_GEN_MAX_BATCH``: running-sequence cap for the
    continuous-batching scheduler — one decode step advances at most
    this many sequences together."""
    return env_int("DEMODEL_GEN_MAX_BATCH", 8, minimum=1)


def gen_queue_limit() -> int:
    """``DEMODEL_GEN_QUEUE``: waiting-queue depth past which admission
    answers 503 + Retry-After (the proxy plane's admission contract,
    applied to generation)."""
    return env_int("DEMODEL_GEN_QUEUE", 64, minimum=1)


def gen_retry_after_s() -> int:
    """``DEMODEL_GEN_RETRY_AFTER``: the Retry-After hint (seconds) a
    queue-overflow 503 carries."""
    return env_int("DEMODEL_GEN_RETRY_AFTER", 1, minimum=1)


def gen_max_new_tokens() -> int:
    """``DEMODEL_GEN_MAX_NEW``: per-request cap on generated tokens —
    admission reserves KV blocks for the WORST CASE (prompt + this cap),
    so the cap is also the no-overcommit bound."""
    return env_int("DEMODEL_GEN_MAX_NEW", 256, minimum=1)


def store_reprobe_secs() -> int:
    """``DEMODEL_STORE_REPROBE_SECS``: how often a node in degraded
    read-through mode re-probes the store with a small real write; a
    successful probe exits the mode automatically. Shared with the
    native proxy's storage maintenance thread."""
    return env_int("DEMODEL_STORE_REPROBE_SECS", 10, minimum=1)


def scrub_interval_secs() -> int:
    """``DEMODEL_SCRUB_INTERVAL_SECS``: seconds between background
    scrubber slices re-digesting committed objects (0, the default,
    disables the scrubber on both planes)."""
    return env_int("DEMODEL_SCRUB_INTERVAL_SECS", 0, minimum=0)


def scrub_rate_mb_s() -> int:
    """``DEMODEL_SCRUB_RATE_MB_S``: the scrubber's re-digest budget in
    MB per second — each slice reads at most ``rate × interval`` bytes,
    so a cold cache is verified slowly enough to never contend with
    serving."""
    return env_int("DEMODEL_SCRUB_RATE_MB_S", 8, minimum=1)
