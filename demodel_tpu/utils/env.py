"""Degrade-not-crash env parsing.

The reference's env handling panics the whole server on config mistakes
(``mo.Result.MustGet``, ``start.go:170-173``); here a malformed value logs a
warning and yields the default — a proxy node must not die because someone
fat-fingered an integer.
"""

from __future__ import annotations

import os

from demodel_tpu.utils.logging import get_logger

log = get_logger("env")

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("", "0", "false", "no", "off")


def env_int(name: str, default: int, minimum: int | None = None) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        log.warning("%s=%r is not an integer; using default %d", name, raw,
                    default)
        return default
    if minimum is not None and val < minimum:
        log.warning("%s=%d below minimum %d; clamping", name, val, minimum)
        return minimum
    return val


def env_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    low = raw.strip().lower()
    if low in _TRUE:
        return True
    if low in _FALSE:
        return False
    log.warning("%s=%r is not a boolean; using default %s", name, raw, default)
    return default


def available_cpus() -> int:
    """CPUs this process may actually run on — sched_getaffinity sees
    cgroup/affinity limits (a container pinned to 1 CPU on a 64-core
    host); cpu_count() is the fallback where affinity is unsupported.
    Concurrency defaults (peer streams, sink prefetch) clamp to this:
    extra threads/sockets only help when cores exist to drain them."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1
