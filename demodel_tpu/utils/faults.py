"""Wire-plane fault tolerance: retry policy + shared peer-health breakers.

The pull/restore/registry plane talks to *friends'* machines over flaky
links ("serve your friends", PAPER.md): peer resets, stalls, and 5xx are
the steady state, not the exception. Every HTTP call on that plane routes
through this module — the ``wire-call-policy`` analyzer rule enforces it —
so the whole wire surface shares one behavior:

- :class:`RetryPolicy` — exponential backoff with **full jitter**, bounded
  by both an attempt cap (``DEMODEL_RETRY_MAX``) and a wall-clock deadline
  (``DEMODEL_RETRY_DEADLINE``), with an explicit retryable-error
  classification (:func:`retryable`): connect errors, resets, timeouts,
  429/5xx, and truncated bodies retry; digest mismatches and other 4xx
  don't — re-reading poisoned bytes or a missing object cannot help.
- :class:`PeerHealth` — a process-wide registry of per-peer
  :class:`CircuitBreaker`\\ s (closed → open after consecutive failures →
  half-open probe after cooldown), shared by the peer shard cache, the
  striping rotation, and manifest discovery: a peer that dies mid-pull
  stops landing on the critical path at full read-timeout for every
  remaining file.
- :func:`request_with_retry` — the one choke point that composes both
  around a ``requests`` call and feeds the retry/breaker counters in
  :mod:`demodel_tpu.utils.metrics`.

Sleeps and clocks are injectable so the whole state machine unit-tests
with a clock stub — no real sleeps on any fast path.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, ClassVar, TypeVar

import requests

from demodel_tpu.utils import metrics, trace
from demodel_tpu.utils.env import env_int
from demodel_tpu.utils.logging import get_logger

log = get_logger("faults")

T = TypeVar("T")


# ------------------------------------------------------------ error taxonomy


class WireError(IOError):
    """A transport-shaped failure worth retrying (reset, truncation, a peer
    answering the wrong protocol) — as opposed to a content-shaped one."""


class TruncatedBody(WireError):
    """The peer promised N bytes and delivered fewer before a clean close —
    retryable: the next attempt resumes at the received offset."""


class RangeIgnored(WireError):
    """The peer answered 200-from-zero to a nonzero Range request.
    NOT retryable against the same peer (it will ignore the next Range
    too — re-dialing a deterministic failure just burns the backoff
    budget and poisons the breaker); :func:`peer_cannot_serve` marks it
    failover-eligible, another peer may do ranges."""


class DigestMismatch(IOError):
    """Delivered bytes hash wrong. NOT retryable: the transfer completed,
    so the wire is fine and the peer's copy (or our expectation) is
    poisoned — re-reading the same object cannot converge."""


class BreakerOpen(IOError):
    """A request was refused locally because the peer's breaker is open."""


#: HTTP statuses a retry can plausibly outlive (408 request-timeout, 429
#: backpressure, and the transient 5xx family — the bounded session pool
#: itself answers 503+Retry-After under flood)
RETRYABLE_STATUS = frozenset({408, 429, 500, 502, 503, 504})


def retryable(exc: BaseException) -> bool:
    """The explicit classification every wire caller shares: transport
    errors, resets, timeouts, 429/5xx and truncated bodies retry; digest
    mismatches, JSON junk, and other 4xx don't."""
    if isinstance(exc, (DigestMismatch, BreakerOpen, RangeIgnored)):
        return False
    if isinstance(exc, WireError):
        return True
    if isinstance(exc, requests.HTTPError):
        resp = exc.response
        if resp is None:
            return True
        return resp.status_code in RETRYABLE_STATUS or resp.status_code >= 500
    if isinstance(exc, ValueError):
        # junk content (incl. requests' JSONDecodeError, which subclasses
        # both ValueError and RequestException): the peer-json-shape
        # degrade contract, not a wire fault — checked BEFORE the generic
        # RequestException arm below
        return False
    if isinstance(exc, (requests.ConnectionError, requests.Timeout)):
        return True
    if isinstance(exc, requests.RequestException):
        # ChunkedEncodingError, ContentDecodingError, … — mid-body
        # transport failures
        return True
    if isinstance(exc, (ConnectionError, TimeoutError)):
        # raw socket resets/timeouts (ConnectionResetError et al.)
        return True
    return False


def peer_cannot_serve(exc: BaseException) -> bool:
    """THIS peer cannot serve THIS object, though the peer is healthy:
    a missing blob (404/410), an unsatisfiable or ignored Range, an
    unimplemented method. Not a health event and not worth a same-peer
    retry — but a rotation holding the same key should try its next
    peer before giving up."""
    if isinstance(exc, RangeIgnored):
        return True
    if isinstance(exc, requests.HTTPError):
        resp = exc.response
        return resp is not None and 400 <= resp.status_code < 500 \
            and resp.status_code not in RETRYABLE_STATUS
    return False


# --------------------------------------------------------------- RetryPolicy


def _default_max_attempts() -> int:
    return env_int("DEMODEL_RETRY_MAX", 4, minimum=1)


def _default_deadline() -> float:
    """Wall-clock budget across all attempts of one logical operation.
    MUST comfortably exceed the largest per-attempt read timeout
    (DEMODEL_PEER_TIMEOUT 120 s windows, 300 s object streams): a
    deadline smaller than one attempt means a first-attempt stall eats
    the whole budget and the failover branch never runs. The attempt cap
    is the primary bound; this is the backstop."""
    return float(env_int("DEMODEL_RETRY_DEADLINE", 600, minimum=1))


def _default_base_delay() -> float:
    return env_int("DEMODEL_RETRY_BASE_MS", 100, minimum=1) / 1000.0


def default_breaker_threshold() -> int:
    return env_int("DEMODEL_BREAKER_THRESHOLD", 3, minimum=1)


def default_breaker_cooldown() -> float:
    return float(env_int("DEMODEL_BREAKER_COOLDOWN", 15, minimum=1))


@dataclass
class RetryPolicy:
    """Exponential backoff with full jitter, capped by attempts AND a
    wall-clock deadline (AWS-style full jitter: ``uniform(0, base·2^k)``
    decorrelates a fleet of pod hosts hammering the same recovering peer).
    """

    max_attempts: int = field(default_factory=_default_max_attempts)
    #: wall-clock budget across ALL attempts of one logical operation
    deadline: float = field(default_factory=_default_deadline)
    base_delay: float = field(default_factory=_default_base_delay)
    max_delay: float = 5.0
    #: injectables — tests swap in stubs; no real sleeps on fast paths
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    rng: random.Random = field(default_factory=random.Random)

    def next_delay(self, attempt: int) -> float:
        """Full-jitter delay before retry number ``attempt`` (1-based)."""
        ceiling = min(self.base_delay * (2 ** max(0, attempt - 1)),
                      self.max_delay)
        return self.rng.uniform(0.0, ceiling)

    def deadline_left(self, start: float) -> float:
        return self.deadline - (self.clock() - start)

    def should_retry(self, attempt: int, start: float,
                     exc: BaseException) -> float | None:
        """The one retry decision, shared by every loop that needs its own
        resume semantics (partial windows, store partials): ``None`` means
        give up (non-retryable / attempt cap / deadline), otherwise the
        jittered, deadline-clipped backoff to sleep before attempt+1."""
        if not retryable(exc):
            return None
        left = self.deadline_left(start)
        if attempt >= self.max_attempts or left <= 0:
            return None
        return min(self.next_delay(attempt), left)

    def call(self, fn: Callable[[], T], *, what: str = "",
             peer: str | None = None,
             health: "PeerHealth | None" = None) -> T:
        """Run ``fn`` under this policy. Retryable failures back off and
        re-try until the attempt cap or deadline; every outcome feeds
        ``health`` (when given) and the retry counters."""
        start = self.clock()
        attempt = 0
        while True:
            attempt += 1
            try:
                result = fn()
            except Exception as e:  # noqa: BLE001 — classified right below
                if health is not None and peer is not None and retryable(e):
                    health.record_failure(peer)
                left = self.deadline_left(start)
                if (not retryable(e) or attempt >= self.max_attempts
                        or left <= 0):
                    raise
                if health is not None and peer is not None \
                        and not health.admissible(peer):
                    # the breaker opened under our own failures: further
                    # same-peer retries are the exact stampede it exists
                    # to stop — surface the cause, not BreakerOpen
                    # (read-only check: this loop is giving up, not
                    # claiming the probe slot)
                    raise
                delay = min(self.next_delay(attempt), max(0.0, left))
                count_retry(peer, delay)
                trace.event("retry", attempt=attempt, peer=peer,
                            error=f"{type(e).__name__}: {e}",
                            backoff_secs=round(delay, 4))
                log.warning("%s failed (%s: %s); retry %d/%d in %.2fs",
                            what or "wire call", type(e).__name__, e,
                            attempt, self.max_attempts - 1, delay)
                self.sleep(delay)
            else:
                if health is not None and peer is not None:
                    health.record_success(peer)
                return result


# ----------------------------------------------------------- circuit breaker

#: ``peer_breaker_state`` gauge values
STATE_CLOSED, STATE_HALF_OPEN, STATE_OPEN = 0, 1, 2

_STATE_NAMES = {STATE_CLOSED: "closed", STATE_HALF_OPEN: "half-open",
                STATE_OPEN: "open"}


class CircuitBreaker:
    """Per-peer breaker: closed → open after ``threshold`` consecutive
    failures → one half-open probe per ``cooldown`` until a success closes
    it again. Thread-safe; the clock is injectable (unit tests drive the
    cooldown with a stub, no real sleeps)."""

    def __init__(self, peer: str, threshold: int, cooldown: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.peer = peer
        self.threshold = threshold
        self.cooldown = cooldown
        self._clock = clock
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_started = 0.0

    def state(self) -> int:
        with self._lock:
            return self._state

    def admissible(self) -> bool:
        """Read-only: could a request go to this peer right now? For pure
        FILTERS (rotation building, locate scans) that may never dial the
        peer — it claims nothing, so it can be called any number of times
        without burning the half-open probe slot (``allow`` claims)."""
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            now = self._clock()
            if self._state == STATE_OPEN:
                return now - self._opened_at >= self.cooldown
            return not (self._probing
                        and now - self._probe_started < self.cooldown)

    def allow(self) -> bool:
        """May a request go to this peer right now? Call this immediately
        before DIALING — an open breaker whose cooldown elapsed admits
        exactly ONE caller as the half-open probe (the claim is this
        call); everyone else keeps being refused until the probe
        reports. A filter that may not dial must use :meth:`admissible`
        instead, or the claimed slot starves the real probe."""
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            now = self._clock()
            if self._state == STATE_OPEN:
                if now - self._opened_at < self.cooldown:
                    return False
                self._set_state(STATE_HALF_OPEN)
                self._probing = True
                self._probe_started = now
                return True
            # half-open: one probe in flight; re-admit if the prober
            # vanished without reporting (died mid-request)
            if self._probing and now - self._probe_started < self.cooldown:
                return False
            self._probing = True
            self._probe_started = now
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != STATE_CLOSED:
                log.info("peer %s breaker closed (probe succeeded)",
                         self.peer)
                self._set_state(STATE_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            failed_probe = self._state == STATE_HALF_OPEN
            self._probing = False
            if self._state == STATE_OPEN:
                # a direct dial past the elapsed cooldown (admissible()
                # filter paths never claim the probe) failed: the peer is
                # still dead — re-arm the cooldown, or admissible() would
                # re-admit it to every rotation forever, one full
                # read-timeout at a time
                self._opened_at = self._clock()
                return
            if failed_probe or (self._state == STATE_CLOSED
                                and self._failures >= self.threshold):
                self._opened_at = self._clock()
                if self._state != STATE_OPEN:
                    self._set_state(STATE_OPEN)
                    metrics.HUB.inc(metrics.labeled(
                        "peer_breaker_open_total", peer=self.peer))
                    log.warning(
                        "peer %s breaker OPEN (%d consecutive failures); "
                        "cooling down %.1fs", self.peer, self._failures,
                        self.cooldown)

    def describe(self) -> dict[str, Any]:
        """Live-state snapshot for ``/debug/statusz``: state name,
        consecutive failures, cooldown, and — when open — how long the
        peer has been cooling (the "which peer is the breaker punishing"
        answer, readable from curl)."""
        with self._lock:
            out: dict[str, Any] = {
                "state": _STATE_NAMES.get(self._state, str(self._state)),
                "failures": self._failures,
                "threshold": self.threshold,
                "cooldown_sec": self.cooldown,
            }
            if self._state != STATE_CLOSED:
                out["open_age_sec"] = round(
                    max(0.0, self._clock() - self._opened_at), 3)
                out["probe_in_flight"] = self._probing
            return out

    def _set_state(self, state: int) -> None:
        # caller holds self._lock
        self._state = state
        # the transition lands on whatever span drove the failing/probing
        # call — the operation that PAID for it (no-op outside a span)
        trace.event("breaker", peer=self.peer,
                    state=_STATE_NAMES.get(state, str(state)))
        metrics.HUB.set_gauge(
            metrics.labeled("peer_breaker_state", peer=self.peer),
            float(state))


class PeerHealth:
    """Process-wide breaker registry, shared by every wire caller so one
    component's failures protect every other component's critical path."""

    # (defaults resolve through module helpers below so the statusz
    # effective-config surface reports the values this class really uses)

    _shared: ClassVar["PeerHealth | None"] = None
    _shared_lock: ClassVar[threading.Lock] = threading.Lock()

    def __init__(self, threshold: int | None = None,
                 cooldown: float | None = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.threshold = (threshold if threshold is not None
                          else default_breaker_threshold())
        self.cooldown = (cooldown if cooldown is not None
                         else default_breaker_cooldown())
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    @classmethod
    def shared(cls) -> "PeerHealth":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls()
            return cls._shared

    @classmethod
    def reset_shared(cls) -> None:
        """Drop the process-wide registry (tests only)."""
        with cls._shared_lock:
            cls._shared = None

    def breaker(self, peer: str) -> CircuitBreaker:
        peer = peer.rstrip("/")
        with self._lock:
            b = self._breakers.get(peer)
            if b is None:
                b = self._breakers[peer] = CircuitBreaker(
                    peer, self.threshold, self.cooldown, self._clock)
            return b

    def allow(self, peer: str) -> bool:
        """Claiming check — call immediately before dialing ``peer``."""
        return self.breaker(peer).allow()

    def admissible(self, peer: str) -> bool:
        """Read-only check — for filters that may never dial ``peer``."""
        return self.breaker(peer).admissible()

    def record_success(self, peer: str) -> None:
        self.breaker(peer).record_success()

    def record_failure(self, peer: str) -> None:
        self.breaker(peer).record_failure()

    def describe(self) -> dict[str, dict[str, Any]]:
        """``peer → breaker snapshot`` for every peer this process has
        talked to (statusz). Read-only: never creates breakers, never
        touches probe slots."""
        with self._lock:
            breakers = dict(self._breakers)
        return {peer: b.describe() for peer, b in sorted(breakers.items())}

    def healthy(self, peers: list[str]) -> list[str]:
        """``peers`` filtered to those the breakers admit, order preserved
        — read-only (:meth:`admissible`), so building a rotation burns no
        probe slots. Falls back to the full list when every breaker
        refuses — a rotation with zero sources would turn a brown-out
        into an outage."""
        alive = [p for p in peers if self.admissible(p)]
        return alive if alive else list(peers)


# ------------------------------------------------------------------ metrics


def count_retry(peer: str | None, delay: float | None = None) -> None:
    """One retry happened against ``peer`` (or an upstream when None);
    ``delay`` (the jittered backoff about to be slept) feeds the
    ``retry_delay_seconds`` histogram — backoff time is invisible wall
    clock unless it lands on the scrape as a distribution."""
    name = "peer_retries_total"
    metrics.HUB.inc(metrics.labeled(name, peer=peer) if peer else name)
    if delay is not None:
        metrics.HUB.observe("retry_delay_seconds", delay)


# ------------------------------------------------------------ request choke


def request_with_retry(
    sender: Any,
    method: str,
    url: str,
    *,
    policy: RetryPolicy | None = None,
    health: PeerHealth | None = None,
    peer: str | None = None,
    ok_statuses: tuple[int, ...] = (),
    check_status: bool = True,
    what: str = "",
    **kw: Any,
) -> requests.Response:
    """THE wire choke point: one HTTP request under breaker + retry policy.

    ``sender`` is a ``requests.Session`` (or the ``requests`` module — both
    expose ``request``). ADMISSION is the caller's job (`health.allow` /
    `health.healthy` before dialing — an allow() on a cooled-down breaker
    IS the half-open probe slot, so re-checking here would refuse the very
    probe the caller was admitted for); this helper feeds the breaker with
    the outcome and stops retrying if it opens mid-loop. ``ok_statuses``
    pass through without raising (e.g. 404 on a manifest probe is an
    answer, not a failure); other non-2xx raise ``requests.HTTPError``,
    classified retryable for 429/5xx only. ``check_status=False`` returns
    whatever arrived (probes that read ``.ok`` themselves).

    Tracing: the whole retried operation runs under one span (retry
    attempts and breaker transitions land on it as events), and the
    span's W3C ``traceparent`` rides the request headers — the server
    side extracts it, so a multi-host pull stitches into one trace.
    """
    pol = policy if policy is not None else RetryPolicy()

    def one_attempt() -> requests.Response:
        r: requests.Response = sender.request(method, url, **kw)
        if check_status and r.status_code not in ok_statuses:
            r.raise_for_status()
        return r

    def run() -> requests.Response:
        return pol.call(one_attempt, what=what or f"{method} {url}",
                        peer=peer, health=health)

    if not trace.active():
        return run()
    with trace.span("http.request", method=method, url=url,
                    peer=peer) as sp:
        kw["headers"] = trace.inject_headers(kw.get("headers"))
        r = run()
        sp.set_attr("status", r.status_code)
        return r
