"""Env-filtered structured logging.

Successor of the legacy generation's ``tracing`` + ``RUST_LOG`` filtering
(reference ``Cargo.lock:475-476``, ``CONTRIBUTING.md:18``): one-line records
tagged ``[demodel-tpu <logger>] <level-letter> <message>``, level set by
``DEMODEL_LOG`` (e.g. ``debug``, ``info``, ``warning``; default info).
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


class _Fmt(logging.Formatter):
    LETTER = {"DEBUG": "D", "INFO": "I", "WARNING": "W", "ERROR": "E",
              "CRITICAL": "C"}

    def format(self, record: logging.LogRecord) -> str:
        letter = self.LETTER.get(record.levelname, "?")
        return f"[demodel-tpu {record.name}] {letter} {record.getMessage()}"


def _configure() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    root = logging.getLogger("demodel_tpu")
    if not root.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(_Fmt())
        root.addHandler(h)
        root.propagate = False
    level = os.environ.get("DEMODEL_LOG", "info").strip().upper()
    root.setLevel(getattr(logging, level, logging.INFO))
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    """Logger ``demodel_tpu.<name>`` under the env-filtered root."""
    _configure()
    return logging.getLogger(f"demodel_tpu.{name}")
