"""Process-wide counters, gauges, histograms + Prometheus exposition.

The reference has no metrics surface at all (SURVEY.md §5 — two
``fmt.Println`` hooks); the rebuild exposes one ``/metrics`` endpoint that
merges three sources: Python-side counters (this HUB), the native proxy's
atomic counters + per-route latency histograms (``dm_proxy_metrics`` JSON),
and store gauges computed from the content-addressed index.

Histograms are fixed log-bucketed (×2 per bucket from 100 µs to ~52 s):
no per-histogram configuration means ``observe()`` is one bisect + three
adds under the hub lock, and every exposition consumer shares one ``le``
schedule — server-side and client-side p99s are directly comparable.
"""

from __future__ import annotations

import logging
import threading
from bisect import bisect_left
from typing import Any, Sequence

#: shared exponential bucket bounds (seconds): 1e-4 · 2^i — 100 µs doubling
#: up to ~52 s, +Inf implicit. One schedule for every duration histogram,
#: Python and native, so cross-surface quantiles line up bucket-for-bucket.
BUCKET_BOUNDS: tuple[float, ...] = tuple(1e-4 * 2 ** i for i in range(20))


def le_str(bound: float) -> str:
    """Canonical ``le`` label text for a bucket bound (``+Inf`` safe)."""
    if bound == float("inf"):
        return "+Inf"
    return "%.6g" % bound


class Histogram:
    """Log-bucketed distribution: counts per bucket (last = +Inf overflow),
    running sum and count. NOT thread-safe on its own — the hub serializes
    ``observe`` under its lock; standalone users bring their own."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Sequence[float] = BUCKET_BOUNDS) -> None:
        self.bounds: tuple[float, ...] = tuple(bounds)
        self.counts: list[int] = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    def quantile(self, q: float) -> float:
        return hist_quantile(self.bounds, self.counts, q)


def hist_quantile(bounds: Sequence[float], counts: Sequence[int],
                  q: float) -> float:
    """Upper-bound quantile estimate from per-bucket (non-cumulative)
    counts: the bound of the bucket holding the q-th sample — the honest
    answer a log-bucketed histogram can give (within one ×2 bucket).
    +Inf-bucket hits report the largest finite bound (there is no upper
    bound to quote). Empty histogram → 0."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = max(1.0, q * total)
    seen = 0
    for i, n in enumerate(counts):
        seen += n
        if seen >= rank and n:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


class Hub:
    """Thread-safe named counters (monotonic), gauges (point-in-time) and
    histograms (log-bucketed distributions).

    Names may carry a Prometheus label suffix built by :func:`labeled`
    (``peer_retries_total{peer="http://a:8080"}``) — the exposition
    groups samples under one ``# TYPE`` line per base name.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, Histogram] = {}

    def inc(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """One histogram sample (seconds for latency series). Creates the
        histogram on first observation — the fixed bucket schedule means
        there is nothing else to configure."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value)

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def get_gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0)

    def get_histogram(self, name: str) -> Histogram | None:
        """Point-in-time copy of one histogram (None when never observed)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                return None
            out = Histogram(h.bounds)
            out.counts = list(h.counts)
            out.sum = h.sum
            out.count = h.count
            return out

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def histograms(self) -> dict[str, dict[str, Any]]:
        """``name → {le, counts, sum, count}`` snapshot (counts per bucket,
        non-cumulative; the exposition cumulates)."""
        with self._lock:
            return {
                name: {"le": list(h.bounds), "counts": list(h.counts),
                       "sum": h.sum, "count": h.count}
                for name, h in self._hists.items()
            }

    def reset(self) -> None:  # tests only
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


HUB = Hub()


def labeled(name: str, **labels: str | None) -> str:
    """``name{key="value",…}`` — the exposition-format sample name for a
    labeled metric (values escaped per Prometheus text format)."""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"")
                     .replace("\n", r"\n"))
        for k, v in sorted(labels.items()) if v is not None)
    return f"{name}{{{inner}}}" if inner else name

#: native proxy metrics that are point-in-time pool state, not monotonic
#: counters — the session executor's live occupancy, queue depth, and the
#: reactor's parked keep-alive connections
PROXY_GAUGES = frozenset({"sessions_active", "sessions_queue_depth",
                          "sessions_parked"})


def _fmt(value: float) -> str:
    return str(int(value)) if float(value).is_integer() else repr(value)


def _emit(lines: list[str], items: dict[str, float], mtype: str) -> None:
    """Samples sorted by name, one ``# TYPE`` line per base metric name
    (labeled samples of one metric sort adjacent and share it)."""
    last_base = None
    for name, value in sorted(items.items()):
        base = name.split("{", 1)[0]
        if base != last_base:
            lines.append(f"# TYPE demodel_{base} {mtype}")
            last_base = base
        lines.append(f"demodel_{name} {_fmt(value)}")


def _with_label(name: str, key: str, value: str) -> str:
    """Splice one more label into a (possibly already-labeled) sample name:
    ``x{a="b"}`` + ``le=0.1`` → ``x{a="b",le="0.1"}``."""
    base, brace, rest = name.partition("{")
    if brace:
        return f'{base}{{{rest[:-1]},{key}="{value}"}}'
    return f'{base}{{{key}="{value}"}}'


def _emit_hist(lines: list[str], prefix: str, name: str,
               le: Sequence[float], counts: Sequence[int], total_sum: float,
               count: int, emitted_types: set[str]) -> None:
    """One histogram series in exposition shape: cumulative ``_bucket``
    samples (one per bound + ``+Inf``), then ``_sum``/``_count``. The
    ``# TYPE`` line is per base name — labeled series of one metric
    (``span=...``, ``route=...``) share it via ``emitted_types``."""
    base = name.split("{", 1)[0]
    metric_base = f"{prefix}{base}"
    if metric_base not in emitted_types:
        emitted_types.add(metric_base)
        lines.append(f"# TYPE {metric_base} histogram")
    cum = 0
    bounds = [*le, float("inf")]
    for bound, n in zip(bounds, counts):
        cum += int(n)
        sample = _with_label(f"{base}_bucket" + name[len(base):],
                             "le", le_str(bound))
        lines.append(f"{prefix}{sample} {cum}")
    labels = name[len(base):]
    lines.append(f"{prefix}{base}_sum{labels} {_fmt(float(total_sum))}")
    lines.append(f"{prefix}{base}_count{labels} {count}")


def render(proxy: Any = None, store: Any = None) -> str:
    """Prometheus text exposition (0.0.4): HUB counters/gauges/histograms
    as ``demodel_<name>``, native proxy counters + per-route histograms as
    ``demodel_proxy_<name>``, store gauges as
    ``demodel_store_{objects,bytes}``."""
    lines: list[str] = []
    _emit(lines, HUB.snapshot(), "counter")
    _emit(lines, HUB.gauges(), "gauge")
    hist_types: set[str] = set()
    for name, h in sorted(HUB.histograms().items()):
        _emit_hist(lines, "demodel_", name, h["le"], h["counts"],
                   h["sum"], h["count"], hist_types)
    if proxy is not None:
        try:
            native = proxy.metrics()
        except Exception:  # noqa: BLE001 — metrics must never take a node down
            native = {}
        hists = native.pop("hist", None)
        for name, value in sorted(native.items()):
            if not isinstance(value, (int, float)):
                continue  # forward-compat: unknown structured sub-objects
            metric = f"demodel_proxy_{name}"
            mtype = "gauge" if name in PROXY_GAUGES else "counter"
            lines.append(f"# TYPE {metric} {mtype}")
            lines.append(f"{metric} {_fmt(value)}")
        if isinstance(hists, dict):
            for family, spec in sorted(hists.items()):
                le = spec.get("le", [])
                for route, h in sorted(spec.get("routes", {}).items()):
                    _emit_hist(lines, "demodel_proxy_",
                               labeled(family, route=route), le,
                               h.get("counts", []), h.get("sum", 0.0),
                               int(h.get("count", 0)), hist_types)
    if store is not None:
        try:
            idx = store.index().get("keys", [])
            lines.append("# TYPE demodel_store_objects gauge")
            lines.append(f"demodel_store_objects {len(idx)}")
            lines.append("# TYPE demodel_store_bytes gauge")
            lines.append(
                f"demodel_store_bytes {sum(e.get('size', 0) for e in idx)}")
            lines.append("# TYPE demodel_store_evictions_total counter")
            lines.append(
                f"demodel_store_evictions_total {store.evictions_total()}")
        except Exception as e:  # noqa: BLE001 — metrics must never take a
            # node down, but a scrape silently missing its store gauges was
            # undiagnosable (no-bare-except finding, PR 1)
            _log().debug("store gauges unavailable: %s", e)
    return "\n".join(lines) + "\n"


def _log() -> logging.Logger:
    """Logger, resolved lazily: utils.metrics must stay import-light (it
    is imported by the native store wrapper during early bring-up)."""
    from demodel_tpu.utils.logging import get_logger

    return get_logger("metrics")
