"""Process-wide counters + Prometheus exposition.

The reference has no metrics surface at all (SURVEY.md §5 — two
``fmt.Println`` hooks); the rebuild exposes one ``/metrics`` endpoint that
merges three sources: Python-side counters (this HUB), the native proxy's
atomic counters (``dm_proxy_metrics`` JSON), and store gauges computed from
the content-addressed index.
"""

from __future__ import annotations

import logging
import threading
from typing import Any


class Hub:
    """Thread-safe named counters (monotonic) and gauges (point-in-time).

    Names may carry a Prometheus label suffix built by :func:`labeled`
    (``peer_retries_total{peer="http://a:8080"}``) — the exposition
    groups samples under one ``# TYPE`` line per base name.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}

    def inc(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def get_gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0)

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def reset(self) -> None:  # tests only
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


HUB = Hub()


def labeled(name: str, **labels: str | None) -> str:
    """``name{key="value",…}`` — the exposition-format sample name for a
    labeled metric (values escaped per Prometheus text format)."""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"")
                     .replace("\n", r"\n"))
        for k, v in sorted(labels.items()) if v is not None)
    return f"{name}{{{inner}}}" if inner else name

#: native proxy metrics that are point-in-time pool state, not monotonic
#: counters — the session executor's live occupancy, queue depth, and the
#: reactor's parked keep-alive connections
PROXY_GAUGES = frozenset({"sessions_active", "sessions_queue_depth",
                          "sessions_parked"})


def _fmt(value: float) -> str:
    return str(int(value)) if float(value).is_integer() else repr(value)


def _emit(lines: list[str], items: dict[str, float], mtype: str) -> None:
    """Samples sorted by name, one ``# TYPE`` line per base metric name
    (labeled samples of one metric sort adjacent and share it)."""
    last_base = None
    for name, value in sorted(items.items()):
        base = name.split("{", 1)[0]
        if base != last_base:
            lines.append(f"# TYPE demodel_{base} {mtype}")
            last_base = base
        lines.append(f"demodel_{name} {_fmt(value)}")


def render(proxy: Any = None, store: Any = None) -> str:
    """Prometheus text exposition (0.0.4): HUB counters/gauges as
    ``demodel_<name>``, native proxy counters as ``demodel_proxy_<name>``,
    store gauges as ``demodel_store_{objects,bytes}``."""
    lines: list[str] = []
    _emit(lines, HUB.snapshot(), "counter")
    _emit(lines, HUB.gauges(), "gauge")
    if proxy is not None:
        try:
            native = proxy.metrics()
        except Exception:  # noqa: BLE001 — metrics must never take a node down
            native = {}
        for name, value in sorted(native.items()):
            metric = f"demodel_proxy_{name}"
            mtype = "gauge" if name in PROXY_GAUGES else "counter"
            lines.append(f"# TYPE {metric} {mtype}")
            lines.append(f"{metric} {_fmt(value)}")
    if store is not None:
        try:
            idx = store.index().get("keys", [])
            lines.append("# TYPE demodel_store_objects gauge")
            lines.append(f"demodel_store_objects {len(idx)}")
            lines.append("# TYPE demodel_store_bytes gauge")
            lines.append(
                f"demodel_store_bytes {sum(e.get('size', 0) for e in idx)}")
            lines.append("# TYPE demodel_store_evictions_total counter")
            lines.append(
                f"demodel_store_evictions_total {store.evictions_total()}")
        except Exception as e:  # noqa: BLE001 — metrics must never take a
            # node down, but a scrape silently missing its store gauges was
            # undiagnosable (no-bare-except finding, PR 1)
            _log().debug("store gauges unavailable: %s", e)
    return "\n".join(lines) + "\n"


def _log() -> logging.Logger:
    """Logger, resolved lazily: utils.metrics must stay import-light (it
    is imported by the native store wrapper during early bring-up)."""
    from demodel_tpu.utils.logging import get_logger

    return get_logger("metrics")
