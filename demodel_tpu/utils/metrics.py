"""Process-wide counters, gauges, histograms + Prometheus exposition.

The reference has no metrics surface at all (SURVEY.md §5 — two
``fmt.Println`` hooks); the rebuild exposes one ``/metrics`` endpoint that
merges three sources: Python-side counters (this HUB), the native proxy's
atomic counters + per-route latency histograms (``dm_proxy_metrics`` JSON),
and store gauges computed from the content-addressed index.

Histograms are fixed log-bucketed (×2 per bucket from 100 µs to ~52 s):
no per-histogram configuration means ``observe()`` is one bisect + three
adds under the hub lock, and every exposition consumer shares one ``le``
schedule — server-side and client-side p99s are directly comparable.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
import weakref
from bisect import bisect_left
from typing import Any, Callable, Sequence

#: shared exponential bucket bounds (seconds): 1e-4 · 2^i — 100 µs doubling
#: up to ~52 s, +Inf implicit. One schedule for every duration histogram,
#: Python and native, so cross-surface quantiles line up bucket-for-bucket.
BUCKET_BOUNDS: tuple[float, ...] = tuple(1e-4 * 2 ** i for i in range(20))


def le_str(bound: float) -> str:
    """Canonical ``le`` label text for a bucket bound (``+Inf`` safe)."""
    if bound == float("inf"):
        return "+Inf"
    return "%.6g" % bound


class Histogram:
    """Log-bucketed distribution: counts per bucket (last = +Inf overflow),
    running sum and count. NOT thread-safe on its own — the hub serializes
    ``observe`` under its lock; standalone users bring their own."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Sequence[float] = BUCKET_BOUNDS) -> None:
        self.bounds: tuple[float, ...] = tuple(bounds)
        self.counts: list[int] = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    def quantile(self, q: float) -> float:
        return hist_quantile(self.bounds, self.counts, q)


def hist_quantile(bounds: Sequence[float], counts: Sequence[int],
                  q: float) -> float:
    """Upper-bound quantile estimate from per-bucket (non-cumulative)
    counts: the bound of the bucket holding the q-th sample — the honest
    answer a log-bucketed histogram can give (within one ×2 bucket).
    +Inf-bucket hits report the largest finite bound (there is no upper
    bound to quote). Empty histogram → 0."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = max(1.0, q * total)
    seen = 0
    for i, n in enumerate(counts):
        seen += n
        if seen >= rank and n:
            return bounds[i] if i < len(bounds) else bounds[-1]
    return bounds[-1]


class Hub:
    """Thread-safe named counters (monotonic), gauges (point-in-time) and
    histograms (log-bucketed distributions).

    Names may carry a Prometheus label suffix built by :func:`labeled`
    (``peer_retries_total{peer="http://a:8080"}``) — the exposition
    groups samples under one ``# TYPE`` line per base name.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, Histogram] = {}
        self._telemetry: "Telemetry | None" = None
        self._telemetry_lock = threading.Lock()

    def inc(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """One histogram sample (seconds for latency series). Creates the
        histogram on first observation — the fixed bucket schedule means
        there is nothing else to configure."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value)

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def get_gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0)

    def get_histogram(self, name: str) -> Histogram | None:
        """Point-in-time copy of one histogram (None when never observed)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                return None
            out = Histogram(h.bounds)
            out.counts = list(h.counts)
            out.sum = h.sum
            out.count = h.count
            return out

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def histograms(self) -> dict[str, dict[str, Any]]:
        """``name → {le, counts, sum, count}`` snapshot (counts per bucket,
        non-cumulative; the exposition cumulates)."""
        with self._lock:
            return {
                name: {"le": list(h.bounds), "counts": list(h.counts),
                       "sum": h.sum, "count": h.count}
                for name, h in self._hists.items()
            }

    # -- time series (the telemetry plane) -----------------------------
    def telemetry(self) -> "Telemetry":
        """This hub's :class:`Telemetry` ring (created on first use)."""
        with self._telemetry_lock:
            if self._telemetry is None:
                self._telemetry = Telemetry(_hub_source(self))
            return self._telemetry

    def rate(self, name: str, window_s: float = 30.0,
             **labels: str | None) -> float:
        """Per-second increase of counter ``name`` over the trailing
        window (0.0 until two snapshots exist). Label kwargs select one
        labeled series: ``rate("peer_retries_total", peer=url)``."""
        return self.telemetry().rate(name, window_s, **labels)

    def window_quantile(self, name: str, q: float,
                        window_s: float = 30.0,
                        **labels: str | None) -> float:
        """Quantile of histogram ``name`` over ONLY the samples observed
        in the trailing window — the delta of the cumulative buckets
        between two ring snapshots, never the lifetime distribution."""
        return self.telemetry().window_quantile(name, q, window_s, **labels)

    def series(self, name: str, **labels: str | None) -> list[dict[str, Any]]:
        """Per-snapshot dump of one family across the telemetry ring."""
        return self.telemetry().series(name, **labels)

    def label_rates(self, base_name: str,
                    window_s: float = 30.0) -> dict[str, float]:
        """Per-series rates of one labeled family (full sample name →
        rate) — :meth:`family_rate` without the aggregation."""
        return self.telemetry().label_rates(base_name, window_s)

    def reset(self) -> None:  # tests only
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
        with self._telemetry_lock:
            if self._telemetry is not None:
                self._telemetry.clear()


HUB = Hub()


def labeled(name: str, **labels: str | None) -> str:
    """``name{key="value",…}`` — the exposition-format sample name for a
    labeled metric (values escaped per Prometheus text format)."""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"")
                     .replace("\n", r"\n"))
        for k, v in sorted(labels.items()) if v is not None)
    return f"{name}{{{inner}}}" if inner else name


_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_labels(name: str) -> tuple[str, dict[str, str]]:
    """Inverse of :func:`labeled`: ``(base family, labels)`` for a sample
    name — how consumers (the fleet per-peer table, the history reader)
    attribute a labeled series back to its peer/span/route."""
    base, brace, rest = name.partition("{")
    if not brace:
        return name, {}
    labels = {
        k: v.replace(r"\n", "\n").replace(r"\"", '"').replace(r"\\", "\\")
        for k, v in _LABEL_RE.findall(rest)
    }
    return base, labels


#: native proxy metrics that are point-in-time pool state, not monotonic
#: counters — the session executor's live occupancy, queue depth, the
#: reactor's parked keep-alive connections, and the writer plane's
#: in-flight EPOLLOUT drains / spliced CONNECT tunnels
PROXY_GAUGES = frozenset({"sessions_active", "sessions_queue_depth",
                          "sessions_parked", "conns_writing",
                          "tunnels_spliced", "store_degraded"})


# ------------------------------------------------------- telemetry plane
#
# Point-in-time counters answer "how many ever"; production triage needs
# "how many per second RIGHT NOW" and "what was the p99 over the last 30
# seconds". The telemetry plane is a bounded in-process ring of periodic
# snapshots (counters, gauges, histogram bucket vectors) over ANY scrape
# source — the Python hub, or the native proxy's metrics JSON diffed
# scrape-over-scrape — with windowed views computed between ring entries:
# counter → rate, gauge → last, histogram → quantile over the DELTA of
# the cumulative buckets (never the lifetime distribution, which a
# long-lived process's history would otherwise dominate).
#
# Sampling is poll-driven, not threaded: every windowed query freshens
# the ring first (rate-limited), so the periodic consumers that exist
# anyway — the tuner tick, ``tools/statusz.py --fleet --watch``, a
# ``/debug/telemetry`` poller — ARE the samplers, and an idle process
# pays nothing. Between two distant polls the window simply stretches to
# the nearest older snapshot (rates divide by real elapsed time, so
# accuracy survives irregular cadence).


def _telemetry_ring_cap() -> int:
    from demodel_tpu.utils.env import env_int

    return env_int("DEMODEL_TELEMETRY_RING", 360, minimum=4)


def _telemetry_min_gap_s() -> float:
    from demodel_tpu.utils.env import env_int

    return env_int("DEMODEL_TELEMETRY_MIN_GAP_MS", 250, minimum=1) / 1000.0


def _hub_source(hub: "Hub") -> Callable[[], dict[str, Any]]:
    def scrape() -> dict[str, Any]:
        hists = hub.histograms()
        return {
            "counters": hub.snapshot(),
            "gauges": hub.gauges(),
            "hists": {
                name: {"le": h["le"], "counts": h["counts"],
                       "sum": h["sum"]}
                for name, h in hists.items()
            },
        }
    return scrape


def native_source(proxy: Any) -> Callable[[], dict[str, Any]]:
    """Scrape source over the native proxy's metrics JSON: flat counters
    split from the known pool gauges, and the per-route ``"hist"`` export
    flattened to ``family{route="..."}`` names — the same windowed views
    as the Python hub, built by diffing successive scrapes in Python.
    Holds only a weak reference: a stopped/collected proxy makes the
    scrape raise, which :meth:`Telemetry.sample` degrades to a skipped
    sample (the ring keeps serving its history)."""
    ref = weakref.ref(proxy)

    def scrape() -> dict[str, Any]:
        p = ref()
        if p is None or not getattr(p, "_h", None):
            raise RuntimeError("native proxy stopped")
        native = p.metrics()
        hists_raw = native.pop("hist", None) or {}
        counters: dict[str, float] = {}
        gauges: dict[str, float] = {}
        for name, value in native.items():
            if not isinstance(value, (int, float)):
                continue
            (gauges if name in PROXY_GAUGES else counters)[name] = value
        hists: dict[str, dict[str, Any]] = {}
        if isinstance(hists_raw, dict):
            for family, spec in hists_raw.items():
                le = list(spec.get("le", []))
                for route, h in spec.get("routes", {}).items():
                    hists[labeled(family, route=route)] = {
                        "le": le, "counts": list(h.get("counts", [])),
                        "sum": float(h.get("sum", 0.0))}
        return {"counters": counters, "gauges": gauges, "hists": hists}
    return scrape


class Telemetry:
    """Bounded ring of scrape snapshots + windowed views over them.

    ``source`` returns one scrape: ``{"counters": {name: v}, "gauges":
    {name: v}, "hists": {name: {"le": [...], "counts": [...], "sum": s}}}``.
    A raising source skips that sample (a stopped native proxy must not
    take the telemetry surface down). ``clock`` is injectable for tests.
    """

    def __init__(self, source: Callable[[], dict[str, Any]],
                 cap: int | None = None, min_gap_s: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self._source = source
        self.cap = cap if cap is not None else _telemetry_ring_cap()
        self.min_gap_s = (min_gap_s if min_gap_s is not None
                          else _telemetry_min_gap_s())
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: list[dict[str, Any]] = []
        #: a freshen() in flight has CLAIMED the next sample — concurrent
        #: freshens return instead of double-sampling (min-gap contract)
        self._freshening = False
        self.samples_taken = 0
        self.samples_failed = 0

    # -- sampling -------------------------------------------------------
    def sample(self) -> bool:
        """Take one snapshot now (True when it landed)."""
        try:
            scrape = self._source()
        except Exception as e:  # noqa: BLE001 — a dead source must not
            # take the telemetry surface (or its caller's plane) down
            with self._lock:
                self.samples_failed += 1
            _log().debug("telemetry scrape failed: %s", e)
            return False
        entry = {
            "ts": self._clock(),
            "wall": time.time(),
            "counters": dict(scrape.get("counters", {})),
            "gauges": dict(scrape.get("gauges", {})),
            "hists": {
                name: (tuple(h.get("le", ())), tuple(h.get("counts", ())),
                       float(h.get("sum", 0.0)))
                for name, h in scrape.get("hists", {}).items()
            },
        }
        with self._lock:
            self._ring.append(entry)
            if len(self._ring) > self.cap:
                del self._ring[: len(self._ring) - self.cap]
            self.samples_taken += 1
        return True

    def freshen(self, max_age_s: float | None = None) -> None:
        """Sample unless the newest snapshot is younger than the gap —
        how poll-driven consumers keep the ring current without a
        dedicated thread (and without flooding it under rapid polls).

        The staleness check and the claim to sample happen under ONE
        lock hold (``_freshening`` is the claim): two consumers polling
        the same stale ring used to BOTH pass the check-then-act gap
        test and land two back-to-back snapshots, violating the min-gap
        contract the ring's sizing assumes (atomic-snapshot finding,
        PR 10 — the scrape itself still runs outside the lock)."""
        gap = max_age_s if max_age_s is not None else self.min_gap_s
        with self._lock:
            newest = self._ring[-1]["ts"] if self._ring else None
            if self._freshening or (newest is not None
                                    and self._clock() - newest < gap):
                return
            self._freshening = True
        try:
            self.sample()
        finally:
            with self._lock:
                self._freshening = False

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def latest(self) -> dict[str, Any] | None:
        """Copy of the newest ring snapshot (None when empty) — what the
        retention archive's flusher diffs window-over-window."""
        with self._lock:
            if not self._ring:
                return None
            e = self._ring[-1]
            return {"ts": e["ts"], "wall": e["wall"],
                    "counters": dict(e["counters"]),
                    "gauges": dict(e["gauges"]),
                    "hists": dict(e["hists"])}

    # -- window selection ----------------------------------------------
    @staticmethod
    def _pair_in(ring: list[dict],
                 window_s: float) -> tuple[dict, dict] | None:
        """(baseline, newest) snapshots ~window_s apart within ``ring``:
        the baseline is the entry closest to ``newest.ts - window_s`` —
        a short ring truncates the window honestly (rates divide by real
        elapsed), and fewer than two snapshots means no window at all."""
        if len(ring) < 2:
            return None
        newest = ring[-1]
        target = newest["ts"] - window_s
        base = min(ring[:-1], key=lambda s: abs(s["ts"] - target))
        return base, newest

    def _pair(self, window_s: float) -> tuple[dict, dict] | None:
        with self._lock:
            ring = list(self._ring)
        return self._pair_in(ring, window_s)

    # -- windowed views -------------------------------------------------
    @staticmethod
    def _rate_between(base: dict, newest: dict, name: str) -> float:
        elapsed = newest["ts"] - base["ts"]
        if elapsed <= 0:
            return 0.0
        now_v = float(newest["counters"].get(name, 0.0))
        old_v = float(base["counters"].get(name, 0.0))
        if now_v < old_v:
            old_v = 0.0  # counter reset (process restart): rate from zero
        return (now_v - old_v) / elapsed

    def rate(self, name: str, window_s: float = 30.0,
             **labels: str | None) -> float:
        if labels:
            name = labeled(name, **labels)
        self.freshen()
        pair = self._pair(window_s)
        if pair is None:
            return 0.0
        return self._rate_between(*pair, name)

    def family_rate(self, base_name: str, window_s: float = 30.0) -> float:
        """Sum of :meth:`rate` over every labeled series of one family
        (``peer_retries_total{peer="..."}`` across all peers)."""
        self.freshen()
        pair = self._pair(window_s)
        if pair is None:
            return 0.0
        base, newest = pair
        prefix = base_name + "{"
        return sum(self._rate_between(base, newest, name)
                   for name in newest["counters"]
                   if name == base_name or name.startswith(prefix))

    def label_rates(self, base_name: str,
                    window_s: float = 30.0) -> dict[str, float]:
        """Per-series rates of one labeled family over the trailing
        window: full sample name → rate, nonzero series only (the
        unlabeled base series included when it exists). The per-peer
        answer :meth:`family_rate`'s sum throws away."""
        self.freshen()
        pair = self._pair(window_s)
        if pair is None:
            return {}
        base, newest = pair
        prefix = base_name + "{"
        out: dict[str, float] = {}
        for name in sorted(newest["counters"]):
            if name == base_name or name.startswith(prefix):
                r = self._rate_between(base, newest, name)
                if r:
                    out[name] = round(r, 6)
        return out

    @staticmethod
    def _delta_between(base: dict, newest: dict,
                       name: str) -> dict[str, Any] | None:
        """Histogram delta between two snapshots: ``{le, counts, sum,
        count, elapsed_s}`` of only the in-between observations,
        reset-safe (a shrunken bucket means the source restarted — the
        baseline is then treated as empty)."""
        now_h = newest["hists"].get(name)
        if now_h is None:
            return None
        le, now_counts, now_sum = now_h
        old_h = base["hists"].get(name)
        if old_h is None or len(old_h[1]) != len(now_counts) \
                or any(n < o for n, o in zip(now_counts, old_h[1])):
            old_counts: Sequence[int] = (0,) * len(now_counts)
            old_sum = 0.0
        else:
            old_counts, old_sum = old_h[1], old_h[2]
        counts = [int(n) - int(o) for n, o in zip(now_counts, old_counts)]
        return {
            "le": list(le), "counts": counts,
            "sum": max(0.0, now_sum - old_sum), "count": sum(counts),
            "elapsed_s": newest["ts"] - base["ts"],
        }

    def window_delta(self, name: str, window_s: float = 30.0,
                     **labels: str | None) -> dict[str, Any] | None:
        """Histogram delta over the trailing window. None when no window
        exists or the family has no snapshots."""
        if labels:
            name = labeled(name, **labels)
        self.freshen()
        pair = self._pair(window_s)
        if pair is None:
            return None
        return self._delta_between(*pair, name)

    def window_quantile(self, name: str, q: float,
                        window_s: float = 30.0,
                        **labels: str | None) -> float:
        d = self.window_delta(name, window_s, **labels)
        if d is None or d["count"] <= 0:
            return 0.0
        return hist_quantile(d["le"], d["counts"], q)

    def series(self, name: str, **labels: str | None) -> list[dict[str, Any]]:
        """The raw ring values of one family, oldest first: counters and
        gauges dump ``value``, histograms ``count``/``sum``."""
        if labels:
            name = labeled(name, **labels)
        with self._lock:
            ring = list(self._ring)
        out: list[dict[str, Any]] = []
        for s in ring:
            if name in s["hists"]:
                _le, counts, hsum = s["hists"][name]
                out.append({"ts": s["wall"], "count": int(sum(counts)),
                            "sum": hsum})
            elif name in s["counters"]:
                out.append({"ts": s["wall"],
                            "value": s["counters"][name]})
            elif name in s["gauges"]:
                out.append({"ts": s["wall"], "value": s["gauges"][name]})
        return out

    def summary(self, windows_s: Sequence[float] = (30.0, 300.0)
                ) -> dict[str, Any]:
        """Every family's windowed view — the ``/debug/telemetry``
        document body: histograms get count/rate/p50/p99 per window,
        counters a rate per window, gauges their last value."""
        self.freshen()
        # ONE ring snapshot under ONE lock hold for the whole document:
        # every family's delta, every counter's rate, the gauges, and
        # the name iteration all derive from the same (baseline, newest)
        # snapshots — a concurrent sample() landing mid-build cannot mix
        # two different windows into one JSON document (and the O(ring)
        # baseline scan runs per window, not per family)
        with self._lock:
            ring = list(self._ring)
        newest = ring[-1] if ring else None
        out: dict[str, Any] = {
            "snapshots": len(ring),
            "windows_s": [int(w) for w in windows_s],
            "hist": {}, "rates": {}, "gauges": {},
        }
        if newest is None:
            return out
        out["gauges"] = dict(newest["gauges"])
        pairs = {w: self._pair_in(ring, w) for w in windows_s}
        for name in sorted(newest["hists"]):
            fam: dict[str, Any] = {}
            for w in windows_s:
                d = (self._delta_between(*pairs[w], name)
                     if pairs[w] is not None else None)
                if d is None:
                    continue
                fam[str(int(w))] = {
                    "count": d["count"],
                    "rate": round(d["count"] / d["elapsed_s"], 6)
                    if d["elapsed_s"] > 0 else 0.0,
                    "p50": hist_quantile(d["le"], d["counts"], 0.5)
                    if d["count"] else 0.0,
                    "p99": hist_quantile(d["le"], d["counts"], 0.99)
                    if d["count"] else 0.0,
                    "sum": round(d["sum"], 6),
                }
            if fam:
                out["hist"][name] = fam
        for name in sorted(newest["counters"]):
            rates = {
                str(int(w)): round(
                    self._rate_between(*pairs[w], name), 6)
                for w in windows_s if pairs[w] is not None}
            if any(v for v in rates.values()):
                out["rates"][name] = rates
        return out


#: per-proxy native telemetry rings, weakly keyed — a stopped proxy's
#: ring falls out with the wrapper object
_native_lock = threading.Lock()
_native_rings: "weakref.WeakKeyDictionary[Any, Telemetry]" = \
    weakref.WeakKeyDictionary()


def native_telemetry(proxy: Any) -> Telemetry:
    """The scrape-diff telemetry ring for one native proxy (created on
    first use; one ring per proxy instance)."""
    with _native_lock:
        tel = _native_rings.get(proxy)
        if tel is None:
            tel = _native_rings[proxy] = Telemetry(native_source(proxy))
        return tel


def telemetry_doc(proxy: Any = None,
                  windows_s: Sequence[float] = (30.0, 300.0)
                  ) -> dict[str, Any]:
    """The ``/debug/telemetry`` JSON document: the Python hub's windowed
    view, plus the native proxy's (scrape-diffed) when one is attached —
    serve-leg AND pull-leg p99s as sliding windows, one curl."""
    doc: dict[str, Any] = {
        "telemetry": 1,
        "time": time.time(),
        "pid": os.getpid(),
        "windows": HUB.telemetry().summary(windows_s),
    }
    if proxy is not None:
        doc["native"] = native_telemetry(proxy).summary(windows_s)
    return doc


def _fmt(value: float) -> str:
    return str(int(value)) if float(value).is_integer() else repr(value)


def _emit(lines: list[str], items: dict[str, float], mtype: str) -> None:
    """Samples sorted by name, one ``# TYPE`` line per base metric name
    (labeled samples of one metric sort adjacent and share it)."""
    last_base = None
    for name, value in sorted(items.items()):
        base = name.split("{", 1)[0]
        if base != last_base:
            lines.append(f"# TYPE demodel_{base} {mtype}")
            last_base = base
        lines.append(f"demodel_{name} {_fmt(value)}")


def _with_label(name: str, key: str, value: str) -> str:
    """Splice one more label into a (possibly already-labeled) sample name:
    ``x{a="b"}`` + ``le=0.1`` → ``x{a="b",le="0.1"}``."""
    base, brace, rest = name.partition("{")
    if brace:
        return f'{base}{{{rest[:-1]},{key}="{value}"}}'
    return f'{base}{{{key}="{value}"}}'


def _emit_hist(lines: list[str], prefix: str, name: str,
               le: Sequence[float], counts: Sequence[int], total_sum: float,
               count: int, emitted_types: set[str]) -> None:
    """One histogram series in exposition shape: cumulative ``_bucket``
    samples (one per bound + ``+Inf``), then ``_sum``/``_count``. The
    ``# TYPE`` line is per base name — labeled series of one metric
    (``span=...``, ``route=...``) share it via ``emitted_types``."""
    base = name.split("{", 1)[0]
    metric_base = f"{prefix}{base}"
    if metric_base not in emitted_types:
        emitted_types.add(metric_base)
        lines.append(f"# TYPE {metric_base} histogram")
    cum = 0
    bounds = [*le, float("inf")]
    for bound, n in zip(bounds, counts):
        cum += int(n)
        sample = _with_label(f"{base}_bucket" + name[len(base):],
                             "le", le_str(bound))
        lines.append(f"{prefix}{sample} {cum}")
    labels = name[len(base):]
    lines.append(f"{prefix}{base}_sum{labels} {_fmt(float(total_sum))}")
    lines.append(f"{prefix}{base}_count{labels} {count}")


def render(proxy: Any = None, store: Any = None) -> str:
    """Prometheus text exposition (0.0.4): HUB counters/gauges/histograms
    as ``demodel_<name>``, native proxy counters + per-route histograms as
    ``demodel_proxy_<name>``, store gauges as
    ``demodel_store_{objects,bytes}``."""
    lines: list[str] = []
    _emit(lines, HUB.snapshot(), "counter")
    _emit(lines, HUB.gauges(), "gauge")
    hist_types: set[str] = set()
    for name, h in sorted(HUB.histograms().items()):
        _emit_hist(lines, "demodel_", name, h["le"], h["counts"],
                   h["sum"], h["count"], hist_types)
    if proxy is not None:
        try:
            native = proxy.metrics()
        except Exception:  # noqa: BLE001 — metrics must never take a node down
            native = {}
        hists = native.pop("hist", None)
        for name, value in sorted(native.items()):
            if not isinstance(value, (int, float)):
                continue  # forward-compat: unknown structured sub-objects
            metric = f"demodel_proxy_{name}"
            mtype = "gauge" if name in PROXY_GAUGES else "counter"
            lines.append(f"# TYPE {metric} {mtype}")
            lines.append(f"{metric} {_fmt(value)}")
        if isinstance(hists, dict):
            for family, spec in sorted(hists.items()):
                le = spec.get("le", [])
                for route, h in sorted(spec.get("routes", {}).items()):
                    _emit_hist(lines, "demodel_proxy_",
                               labeled(family, route=route), le,
                               h.get("counts", []), h.get("sum", 0.0),
                               int(h.get("count", 0)), hist_types)
    if store is not None:
        try:
            idx = store.index().get("keys", [])
            lines.append("# TYPE demodel_store_objects gauge")
            lines.append(f"demodel_store_objects {len(idx)}")
            lines.append("# TYPE demodel_store_bytes gauge")
            lines.append(
                f"demodel_store_bytes {sum(e.get('size', 0) for e in idx)}")
            lines.append("# TYPE demodel_store_evictions_total counter")
            lines.append(
                f"demodel_store_evictions_total {store.evictions_total()}")
        except Exception as e:  # noqa: BLE001 — metrics must never take a
            # node down, but a scrape silently missing its store gauges was
            # undiagnosable (no-bare-except finding, PR 1)
            _log().debug("store gauges unavailable: %s", e)
    return "\n".join(lines) + "\n"


def _log() -> logging.Logger:
    """Logger, resolved lazily: utils.metrics must stay import-light (it
    is imported by the native store wrapper during early bring-up)."""
    from demodel_tpu.utils.logging import get_logger

    return get_logger("metrics")
