"""Continuous sampling profiler — the "why is it slow" layer.

Telemetry (PR 9) says *what* is slow (per-stage p99s) and retention
(PR 11) says *when* it got slow; this module answers *why*: which frames
were on-CPU and which were parked when ``window-read`` p99 doubled.
Google-Wide-Profiling-style always-on sampling (Ren et al., 2010),
joined to the Dapper-style span context :mod:`demodel_tpu.utils.trace`
already propagates.

Design, smallest-thing-that-works:

- a daemon **sampler thread** walks ``sys._current_frames()`` at
  ``DEMODEL_PROFILE_HZ`` (default 19 — deliberately off the common
  10/100 Hz beat so round-rate periodic work doesn't alias), folds each
  thread's stack into a Brendan-Gregg collapsed key
  (``seg;seg;seg``) and bumps a bounded aggregate
  (``DEMODEL_PROFILE_MAX_STACKS``; past the bound stacks fold into
  ``(other)`` and a drop counter).
- **span attribution**: every sample's folded key is rooted at the
  innermost *live* span on that thread (from the trace in-flight
  registry, joined by the span's starting-thread ident) — so a profile
  slices by pull stage (``window-read``, ``place``, ``budget-wait``, …).
  The join between traces and profiles none of the other planes has.
- **wall vs on-CPU**: each sampled thread's per-thread CPU clock
  (Linux ``CPUCLOCK_SCHED | CPUCLOCK_PERTHREAD``, fallback
  ``/proc/self/task/<tid>/schedstat``, else wall-only) decides whether
  the tick found it running or parked — a lock convoy shows as wall
  samples with no CPU, a hot loop as both.
- **windows**: the aggregate rolls every ``DEMODEL_PROFILE_WINDOW_S``
  into a bounded pending queue the retention plane drains into the
  ``TelemetryArchive`` (``kind="profile"`` records — profiles survive
  restarts and ship with ``--fleet --watch --ship``).
- **capture** (the ``/debug/profile`` contract): snapshot the cumulative
  aggregate, sleep, snapshot again, diff — so concurrent captures never
  consume each other's (or the archive's) baseline.

Observability tiers follow :mod:`trace`: the profiler runs under
export/observe and ``DEMODEL_OBS=0`` kills it entirely —
:func:`ensure` then returns ``None`` and no thread ever starts.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Any

from demodel_tpu.utils import trace
from demodel_tpu.utils.env import (
    profile_hz,
    profile_max_stacks,
    profile_window_s,
)
from demodel_tpu.utils.logging import get_logger

log = get_logger("profiler")

#: frames deeper than this truncate (the aggregate key must stay small)
_MAX_DEPTH = 64
#: stacks kept verbatim per archived window; the tail rolls into (other)
_WINDOW_TOP = 128
#: pending archive windows (retention drains; bounded if it never does)
_PENDING_CAP = 8

# Linux per-thread CPU clockid for another thread, as pthread_getcpuclockid
# would build it: CPUCLOCK_SCHED (2) | CPUCLOCK_PERTHREAD_MASK (4), tid in
# the upper bits. Negative by construction — that is how dynamic clock ids
# are spelled.
_CPUCLOCK_SCHED_PERTHREAD = 6


def _thread_cpu_clockid(native_tid: int) -> int:
    return ((~native_tid) << 3) | _CPUCLOCK_SCHED_PERTHREAD


class Profiler:
    """One sampler thread + bounded folded-stack aggregates.

    Normally a process-wide singleton via :func:`ensure`; tests build
    private instances with small knobs.
    """

    def __init__(self, hz: int | None = None,
                 max_stacks: int | None = None,
                 window_s: float | None = None) -> None:
        self.hz = int(hz) if hz else profile_hz()
        self.max_stacks = int(max_stacks) if max_stacks else (
            profile_max_stacks())
        self.window_s = float(window_s) if window_s else float(
            profile_window_s())
        self._lock = threading.Lock()
        #: folded stack -> [wall_samples, cpu_samples]; never reset
        self._cum: dict[str, list[int]] = {}
        #: same shape, reset every window roll
        self._win: dict[str, list[int]] = {}
        self._samples = 0          # cumulative sampled thread-ticks
        self._win_samples = 0
        self._dropped = 0          # cumulative stacks folded to (other)
        self._win_dropped = 0
        self._errors = 0           # swallowed tick failures
        self._windows_rolled = 0
        self._pending: deque[dict[str, Any]] = deque(maxlen=_PENDING_CAP)
        self._last_window: dict[str, Any] | None = None
        self._win_t0 = 0.0         # monotonic start of current window
        #: temporary rate override (capture ``hz=`` query param); 0 = none
        self._hz_override = 0
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        # -- CPU-clock strategy: resolved here, immutable afterwards ----
        self._cpu_mode = self._resolve_cpu_mode()
        self._native_by_ident: dict[int, int] = {}
        self._cpu_last: dict[int, tuple[float, float]] = {}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        with self._lock:
            self._win_t0 = time.monotonic()
        self._stop_evt.clear()
        t = threading.Thread(target=self._run, daemon=True,
                             name="demodel-profiler")
        self._thread = t
        t.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout=5.0)
        self._thread = None

    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # ------------------------------------------------------------- sampling

    def _resolve_cpu_mode(self) -> str | None:
        """Pick the cheapest per-thread CPU read this kernel offers."""
        try:
            nid = threading.get_native_id()
        except AttributeError:
            return None
        try:
            time.clock_gettime(_thread_cpu_clockid(nid))
            return "clock"
        except (OSError, OverflowError, ValueError):
            pass
        try:
            with open(f"/proc/self/task/{nid}/schedstat", "rb") as f:
                int(f.read().split()[0])
            return "schedstat"
        except (OSError, ValueError, IndexError):
            return None

    def _read_cpu(self, native_tid: int) -> float | None:
        mode = self._cpu_mode
        if mode == "clock":
            try:
                return time.clock_gettime(_thread_cpu_clockid(native_tid))
            except (OSError, OverflowError, ValueError):
                return None
        if mode == "schedstat":
            try:
                path = f"/proc/self/task/{native_tid}/schedstat"
                with open(path, "rb") as f:
                    return int(f.read().split()[0]) / 1e9
            except (OSError, ValueError, IndexError):
                return None
        return None

    def _refresh_native_ids(self) -> None:
        """ident→kernel-tid map from the live thread list; prunes CPU
        bookkeeping for threads that exited (the maps must not grow with
        thread churn)."""
        fresh: dict[int, int] = {}
        for t in threading.enumerate():
            nid = getattr(t, "native_id", None)
            if t.ident is not None and nid is not None:
                fresh[t.ident] = nid
        self._native_by_ident = fresh
        live = set(fresh.values())
        self._cpu_last = {k: v for k, v in self._cpu_last.items()
                          if k in live}

    def _on_cpu(self, ident: int, now: float) -> bool:
        """Did this thread burn CPU since its previous tick? (>= half the
        inter-tick wall time counts as running; the first observation of
        a thread has no baseline and reads as parked.)"""
        nid = self._native_by_ident.get(ident)
        if nid is None:
            self._refresh_native_ids()
            nid = self._native_by_ident.get(ident)
            if nid is None:
                return False
        cpu = self._read_cpu(nid)
        if cpu is None:
            return False
        last = self._cpu_last.get(nid)
        self._cpu_last[nid] = (cpu, now)
        if last is None:
            return False
        wall_d = now - last[1]
        return wall_d > 0 and (cpu - last[0]) >= 0.5 * wall_d

    @staticmethod
    def _fold(frame: Any, span_name: str | None) -> str:
        """Collapsed key, root-first, span segment first: Brendan Gregg's
        fold format with the trace join baked into the hierarchy."""
        segs: list[str] = []
        f = frame
        depth = 0
        while f is not None and depth < _MAX_DEPTH:
            co = f.f_code
            base = co.co_filename.rsplit("/", 1)[-1]
            if base.endswith(".py"):
                base = base[:-3]
            name = getattr(co, "co_qualname", None) or co.co_name
            segs.append(f"{base}:{name}")
            f = f.f_back
            depth += 1
        root = (span_name or "-").replace(";", ",").replace(" ", "_")
        segs.append(root)
        segs.reverse()
        return ";".join(segs)

    def _spans_by_thread(self) -> dict[int, str]:
        """Innermost live span name per starting-thread ident — the
        trace↔profile join. Innermost = the live span with the latest
        start on that thread (children start after parents)."""
        best: dict[int, tuple[float, str]] = {}
        with trace._inflight_lock:
            spans = list(trace._inflight.values())
        for s in spans:
            if s.dur is not None:
                continue
            tid = s._thread_ident
            if tid is None:
                continue
            cur = best.get(tid)
            if cur is None or s._t0 > cur[0]:
                best[tid] = (s._t0, s.name)
        return {tid: name for tid, (_, name) in best.items()}

    def _bump(self, agg: dict[str, list[int]], folded: str,
              on_cpu: bool) -> bool:
        """Returns True when the stack was folded into (other)."""
        ent = agg.get(folded)
        dropped = False
        if ent is None:
            if len(agg) >= self.max_stacks:
                dropped = True
                ent = agg.get("(other)")
                if ent is None:
                    ent = agg["(other)"] = [0, 0]
            else:
                ent = agg[folded] = [0, 0]
        ent[0] += 1
        if on_cpu:
            ent[1] += 1
        return dropped

    def _tick(self) -> None:
        frames = sys._current_frames()
        now = time.perf_counter()
        span_by_tid = self._spans_by_thread()
        me = threading.get_ident()
        samples: list[tuple[str, bool]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue  # the sampler never profiles itself
            folded = self._fold(frame, span_by_tid.get(ident))
            samples.append((folded, self._on_cpu(ident, now)))
        del frames  # drop frame refs promptly — they pin locals alive
        n_dropped = 0
        with self._lock:
            for folded, on_cpu in samples:
                if self._bump(self._cum, folded, on_cpu):
                    n_dropped += 1
                self._bump(self._win, folded, on_cpu)
            self._samples += len(samples)
            self._win_samples += len(samples)
            self._dropped += n_dropped
            self._win_dropped += n_dropped
        from demodel_tpu.utils import metrics

        metrics.HUB.inc("profiler_samples_total", len(samples))
        if n_dropped:
            metrics.HUB.inc("profiler_stacks_dropped_total", n_dropped)

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            with self._lock:
                hz = self._hz_override or self.hz
            period = 1.0 / max(1, hz)
            t0 = time.perf_counter()
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — the profiler must
                # never take the plane down; count and keep sampling
                with self._lock:
                    self._errors += 1
                    errors = self._errors
                if errors <= 3:
                    log.warning("profiler tick failed: %s", e)
            self._roll_window()
            elapsed = time.perf_counter() - t0
            self._stop_evt.wait(max(0.001, period - elapsed))

    # -------------------------------------------------------------- windows

    def _roll_window(self, force: bool = False) -> None:
        """Roll the window aggregate into a pending archive record when
        the window elapsed (always, under ``force`` — tests). The
        elapsed check and the swap share one lock hold: checking outside
        would race a concurrent roll and double-emit."""
        now_mono = time.monotonic()
        with self._lock:
            if not force and now_mono - self._win_t0 < self.window_s:
                return
            win, self._win = self._win, {}
            samples, self._win_samples = self._win_samples, 0
            dropped, self._win_dropped = self._win_dropped, 0
            hz = self._hz_override or self.hz
            dur = max(0.0, now_mono - self._win_t0)
            self._win_t0 = now_mono
            self._windows_rolled += 1
        rec = {
            "kind": "profile",
            "plane": "python",
            "ts": time.time(),
            "window_s": round(dur, 3),
            "hz": hz,
            "samples": samples,
            "dropped": dropped,
            "cpu_mode": self._cpu_mode,
            "stacks": _top_stacks(win, _WINDOW_TOP),
        }
        with self._lock:
            self._pending.append(rec)
            self._last_window = rec
        # stale-thread hygiene rides the window cadence
        self._refresh_native_ids()

    def drain_windows(self) -> list[dict[str, Any]]:
        """Pop every pending window record (the retention flush path)."""
        out: list[dict[str, Any]] = []
        while True:
            try:
                out.append(self._pending.popleft())
            except IndexError:
                return out

    def last_window(self) -> dict[str, Any] | None:
        with self._lock:
            return self._last_window

    def partial_window(self) -> dict[str, Any]:
        """The current (un-rolled) window as a record — read-only; the
        archive baseline is untouched. What SIGUSR2 embeds when no full
        window has rolled yet."""
        with self._lock:
            win = {k: list(v) for k, v in self._win.items()}
            samples = self._win_samples
            dropped = self._win_dropped
            hz = self._hz_override or self.hz
            win_t0 = self._win_t0
        return {
            "kind": "profile",
            "plane": "python",
            "ts": time.time(),
            "window_s": round(max(0.0, time.monotonic() - win_t0), 3),
            "hz": hz,
            "samples": samples,
            "dropped": dropped,
            "cpu_mode": self._cpu_mode,
            "partial": True,
            "stacks": _top_stacks(win, _WINDOW_TOP),
        }

    # -------------------------------------------------------------- capture

    def snapshot(self) -> dict[str, list[int]]:
        """Copy of the cumulative aggregate (stack -> [wall, cpu])."""
        with self._lock:
            return {k: list(v) for k, v in self._cum.items()}

    def capture(self, seconds: float = 1.0, hz: int = 0) -> dict[str, Any]:
        """The ``/debug/profile`` semantics: cumulative snapshot, sleep,
        snapshot, diff. ``seconds=0`` returns the whole cumulative
        aggregate without sleeping; ``hz`` temporarily overrides the
        sampling rate for the capture's duration."""
        seconds = max(0.0, min(float(seconds), 60.0))
        with self._lock:
            prev_override = self._hz_override
            if hz > 0:
                self._hz_override = min(int(hz), 1000)
        try:
            if seconds > 0:
                before = self.snapshot()
                time.sleep(seconds)
                after = self.snapshot()
                diff: dict[str, list[int]] = {}
                for k, v in after.items():
                    b = before.get(k)
                    wall = v[0] - (b[0] if b else 0)
                    cpu = v[1] - (b[1] if b else 0)
                    if wall > 0 or cpu > 0:
                        diff[k] = [wall, cpu]
            else:
                diff = self.snapshot()
        finally:
            # demodel: allow(atomic-snapshot) — save/restore of an
            # advisory rate override: concurrent captures race benignly
            # (last restore wins; the sampler just reads whatever is
            # current each tick)
            with self._lock:
                self._hz_override = prev_override
        stacks = _top_stacks(diff, None)
        return {
            "plane": "python",
            "hz": hz or self.hz,
            "seconds": seconds,
            "samples": sum(s["wall"] for s in stacks),
            "cpu_mode": self._cpu_mode,
            "stacks": stacks,
        }

    # ------------------------------------------------------------- statusz

    def describe(self) -> dict[str, Any]:
        with self._lock:
            n_stacks = len(self._cum)
            samples = self._samples
            dropped = self._dropped
            errors = self._errors
            rolled = self._windows_rolled
        return {
            "running": self.alive(),
            "hz": self.hz,
            "cpu_mode": self._cpu_mode,
            "samples": samples,
            "stacks": n_stacks,
            "dropped": dropped,
            "errors": errors,
            "windows_rolled": rolled,
            "window_s": self.window_s,
        }


def _top_stacks(agg: dict[str, list[int]],
                top: int | None) -> list[dict[str, Any]]:
    """Aggregate dict → sorted stack entries, heaviest wall first;
    past ``top`` the tail rolls into one ``(other)`` entry (archive
    records must stay bounded regardless of stack diversity)."""
    rows = sorted(agg.items(), key=lambda kv: (-kv[1][0], kv[0]))
    out = [{"stack": k, "wall": v[0], "cpu": v[1]}
           for k, v in (rows if top is None else rows[:top])]
    if top is not None and len(rows) > top:
        wall = sum(v[0] for _, v in rows[top:])
        cpu = sum(v[1] for _, v in rows[top:])
        out.append({"stack": "(other)", "wall": wall, "cpu": cpu})
    return out


def collapse(profile: dict[str, Any]) -> str:
    """A capture/window record → collapsed text (``stack count`` lines,
    wall samples — the flamegraph.pl / speedscope contract). The CPU
    split stays JSON-only; collapsed is the lowest-common-denominator
    interchange the bench legs and ``profile_report.py`` share."""
    lines = [f"{s['stack']} {s['wall']}" for s in profile.get("stacks", ())
             if s.get("wall", 0) > 0]
    return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------- process-wide singleton

_glock = threading.Lock()
_profiler: Profiler | None = None


def ensure() -> Profiler | None:
    """Start (or return) the process profiler. ``None`` when the
    observability tier is fully off (``DEMODEL_OBS=0``) — no thread, no
    allocation beyond this check: the zero-cost contract."""
    global _profiler
    if not trace.active():
        return None
    with _glock:
        p = _profiler
        if p is None or not p.alive():
            p = Profiler()  # demodel: allow(no-blocking-io-under-lock) — the CPU-clock probe reads one 2-line /proc schedstat file, once per process, and only on kernels without per-thread clock_gettime
            p.start()  # demodel: allow(no-blocking-io-under-lock) — start() only spawns the daemon sampler; the open() the call-graph walk reaches runs on THAT thread, never under _glock
            _profiler = p
        return p


def current() -> Profiler | None:
    """The running profiler, or None — never starts one (the peek the
    dep-light surfaces use)."""
    return _profiler


def stop() -> None:
    global _profiler
    with _glock:
        p, _profiler = _profiler, None
    if p is not None:
        p.stop()


def _reset_for_tests() -> None:
    stop()


def capture(seconds: float = 1.0, hz: int = 0) -> dict[str, Any] | None:
    """Module-level capture against the singleton (starting it if the
    tier allows); the ``/debug/profile`` handlers call this."""
    p = ensure()
    if p is None:
        return None
    return p.capture(seconds=seconds, hz=hz)


def drain_windows() -> list[dict[str, Any]]:
    """Pending archive windows from the singleton (retention flush glue;
    empty when the profiler never started)."""
    p = _profiler
    return p.drain_windows() if p is not None else []


def recorder_window() -> dict[str, Any] | None:
    """What a flight-recorder dump embeds: the last rolled window, else
    the live partial window — never consumes the archive queue."""
    p = _profiler
    if p is None:
        return None
    return p.last_window() or p.partial_window()


def describe() -> dict[str, Any] | None:
    """Statusz section (None when not running)."""
    p = _profiler
    return p.describe() if p is not None else None
