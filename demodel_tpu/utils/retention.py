"""Durable telemetry history: the retention plane.

The ``/debug/telemetry`` ring (:mod:`demodel_tpu.utils.metrics`) dies
with the process — "what happened during last night's cold boot" is
unanswerable once the node that saw it restarts. This module gives each
window a second life on disk:

- :class:`TelemetryArchive` owns a directory of **gzipped JSONL
  segments**. Every record is appended as ONE complete gzip member
  (members concatenate into a legal stream), so a crash mid-append
  leaves at most a truncated tail member that the reader tolerates —
  rotation needs no fsync choreography to stay crash-safe.
- A background **flusher** samples each attached
  :class:`~demodel_tpu.utils.metrics.Telemetry` ring (the hub and, when
  a proxy is wired, the native mirror), diffs consecutive snapshots
  reset-safely, and appends one compact *window record* per new
  snapshot: counter deltas, gauge lasts, histogram bucket deltas.
- **Retention budgets**: segments rotate at a byte threshold and the
  oldest are evicted while the directory exceeds
  ``DEMODEL_TELEMETRY_RETAIN_MB`` or ages past
  ``DEMODEL_TELEMETRY_RETAIN_HOURS``.
- Segment names embed wall-clock start, pid, and a sequence number, so
  a **restarted node appends next to its previous incarnation's
  history** and :meth:`TelemetryArchive.history` reads one continuous
  per-family series across both.

Everything here is stdlib-only and import-light: the restore server
only imports this module when ``DEMODEL_TELEMETRY_ARCHIVE`` is set, so
the archive-disabled path is byte-identical to a tree without this
file.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Callable

from demodel_tpu.utils import metrics
from demodel_tpu.utils.env import (
    env_int,
    telemetry_archive_dir,
    telemetry_retain_hours,
    telemetry_retain_mb,
)
from demodel_tpu.utils.logging import get_logger

log = get_logger("retention")

_SEGMENT_PREFIX = "telemetry-"
_SEGMENT_SUFFIX = ".jsonl.gz"

#: the archive's own meta-counters: live on /metrics and /debug/telemetry
#: but excluded from window records — archiving the act of archiving
#: would keep every otherwise-quiet window alive (write → counter inc →
#: next window non-quiet → write → …)
_SELF_FAMILIES = frozenset({
    "telemetry_archive_records_total",
    "telemetry_segments_evicted_total",
})


def _flush_gap_s() -> float:
    return env_int("DEMODEL_TELEMETRY_FLUSH_MS", 1000, minimum=20) / 1000.0


def _default_segment_bytes() -> int:
    return env_int("DEMODEL_TELEMETRY_SEGMENT_KB", 256, minimum=1) << 10


def _segment_start_ms(path: Path) -> int:
    """Wall-clock start embedded in a segment name (0 when unparseable —
    sorts foreign files first so they are evicted before real history)."""
    stem = path.name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
    head = stem.split("-", 1)[0]
    try:
        return int(head)
    except ValueError:
        return 0


def read_segment(path: Path) -> list[dict[str, Any]]:
    """Decode one segment, tolerating a truncated tail member.

    A crash mid-append leaves the final gzip member incomplete; reading
    in small chunks keeps everything decoded before the stream breaks,
    and only complete newline-terminated JSON lines are kept — the torn
    tail is dropped, never raised.
    """
    try:
        data = path.read_bytes()
    except OSError:
        return []
    raw = bytearray()
    pos = 0
    # member-by-member: a torn/garbage tail member must not poison the
    # complete members before it (a single buffered gzip read would —
    # it fills its buffer ACROSS members before surfacing the error)
    while pos < len(data):
        decomp = zlib.decompressobj(wbits=31)
        try:
            raw += decomp.decompress(data[pos:])
        except zlib.error:
            break  # corrupt tail member — keep prior members
        if not decomp.eof:
            break  # truncated tail member — keep its decoded prefix
        if not decomp.unused_data:
            break
        pos = len(data) - len(decomp.unused_data)
    records: list[dict[str, Any]] = []
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn line inside the truncated member
        if isinstance(rec, dict):
            records.append(rec)
    return records


def _base_name(name: str) -> str:
    return name.partition("{")[0]


def _matches(name: str, family: str | None, label: str | None) -> bool:
    if family is not None and _base_name(name) != family:
        return False
    if label:
        key, sep, value = label.partition("=")
        needle = f'{key}="{value}"' if sep else label
        brace = name.partition("{")[2]
        if needle not in brace:
            return False
    return True


class TelemetryArchive:
    """Append-only archive of telemetry windows under one directory.

    Also reused bare (no attached rings) by ``tools/statusz.py --ship``,
    which :meth:`append`\\ s fleet-watch ticks into a pod-level archive.
    """

    def __init__(self, root: Path, *, retain_mb: int | None = None,
                 retain_hours: float | None = None,
                 segment_bytes: int | None = None,
                 flush_s: float | None = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.retain_bytes = (retain_mb if retain_mb is not None
                             else telemetry_retain_mb()) << 20
        self.retain_s = (retain_hours if retain_hours is not None
                         else float(telemetry_retain_hours())) * 3600.0
        self.segment_bytes = (segment_bytes if segment_bytes is not None
                              else _default_segment_bytes())
        self.flush_s = flush_s if flush_s is not None else _flush_gap_s()
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._active: Path | None = None
        self._sources: dict[str, metrics.Telemetry] = {}
        self._prev: dict[str, dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.records_written = 0
        self.segments_evicted = 0

    # ------------------------------------------------------------ write
    def _next_segment(self) -> Path:
        self._seq += 1
        # seq zero-padded so the (start_ms, name) sort stays correct
        # when many segments share one wall-clock millisecond
        name = (f"{_SEGMENT_PREFIX}{int(self._clock() * 1000):013d}"
                f"-{os.getpid()}-{self._seq:06d}{_SEGMENT_SUFFIX}")
        return self.root / name

    def append(self, record: dict[str, Any]) -> None:
        """Append one record as a complete gzip member (crash-safe unit)."""
        member = gzip.compress(
            (json.dumps(record, separators=(",", ":")) + "\n").encode())
        with self._lock:
            if (self._active is None
                    or not self._active.exists()
                    or self._active.stat().st_size + len(member)
                    > self.segment_bytes):
                self._active = self._next_segment()
                self._enforce_retention_locked()
            with open(self._active, "ab") as f:  # demodel: allow(no-blocking-io-under-lock) — the writer lock IS the file-handle serializer: rotation picks the segment and the append lands in it atomically; contention is one flusher thread plus a rare endpoint flush_once
                f.write(member)
            self.records_written += 1
        metrics.HUB.inc("telemetry_archive_records_total")

    def _enforce_retention_locked(self) -> None:
        """Evict oldest closed segments past the byte/age budgets."""
        segments = self.segments()
        now = self._clock()
        total = 0
        sizes: dict[Path, int] = {}
        for seg in segments:
            try:
                sizes[seg] = seg.stat().st_size
                total += sizes[seg]
            except OSError:
                sizes[seg] = 0
        for seg in segments:
            if seg == self._active:
                continue  # never evict the segment being written
            over_bytes = total > self.retain_bytes
            try:
                over_age = (now - seg.stat().st_mtime) > self.retain_s
            except OSError:
                over_age = True
            if not (over_bytes or over_age):
                break  # oldest-first: the first keeper keeps the rest
            try:
                seg.unlink()
            except OSError:
                continue
            total -= sizes.get(seg, 0)
            self.segments_evicted += 1
            metrics.HUB.inc("telemetry_segments_evicted_total")

    # ---------------------------------------------------------- flusher
    def attach(self, name: str, telemetry: metrics.Telemetry) -> None:
        """Register a telemetry ring whose windows this archive persists."""
        with self._lock:
            self._sources[name] = telemetry

    def attach_native(self, proxy: Any) -> None:
        """Attach the native mirror once (later calls are no-ops)."""
        with self._lock:
            if "native" in self._sources:
                return
        self.attach("native", metrics.native_telemetry(proxy))

    def flush_once(self) -> int:
        """Sample every attached ring once; append a window record per
        ring that produced a NEW snapshot since the last flush. Returns
        how many records were appended."""
        with self._lock:
            sources = dict(self._sources)
        pending: list[dict[str, Any]] = []
        for name, tel in sources.items():
            try:
                tel.freshen()
                cur = tel.latest()
            except Exception:
                log.exception("telemetry flush failed for %s", name)
                continue
            if cur is None:
                continue
            with self._lock:
                prev = self._prev.get(name)
                self._prev[name] = cur
            if prev is None or cur["ts"] <= prev["ts"]:
                continue  # first sighting is the baseline, not a window
            rec = _window_record(name, prev, cur)
            if rec is not None:
                pending.append(rec)
        # the profiler plane rides the same flush cadence: drain any
        # rolled profile windows into the archive (sys.modules peek — the
        # flusher must not be the thing that imports, let alone starts,
        # the sampler). kind="profile" records carry no counters/gauges/
        # hists keys, so history() skips them by design; profiles() reads
        # them back.
        import sys as _sys

        prof = _sys.modules.get("demodel_tpu.utils.profiler")
        if prof is not None:
            try:
                pending.extend(prof.drain_windows())
            except Exception:
                log.exception("profile window drain failed")
        for rec in pending:
            self.append(rec)
        return len(pending)

    def start(self) -> "TelemetryArchive":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="telemetry-archive", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.flush_s):
            try:
                self.flush_once()
            except Exception:
                log.exception("telemetry archive flush crashed")

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5.0)

    def close(self) -> None:
        self.stop()
        try:
            self.flush_once()
        except Exception:
            log.exception("final telemetry flush failed")

    # ------------------------------------------------------------- read
    def segments(self) -> list[Path]:
        """All segments, oldest first (wall-clock start, then pid/seq)."""
        try:
            found = [p for p in self.root.iterdir()
                     if p.name.startswith(_SEGMENT_PREFIX)
                     and p.name.endswith(_SEGMENT_SUFFIX)]
        except OSError:
            return []
        return sorted(found, key=lambda p: (_segment_start_ms(p), p.name))

    def records(self) -> list[dict[str, Any]]:
        """Every decodable record across all segments, in segment order."""
        out: list[dict[str, Any]] = []
        for seg in self.segments():
            out.extend(read_segment(seg))
        return out

    def history(self, family: str | None = None, label: str | None = None,
                since: float | None = None,
                until: float | None = None) -> dict[str, Any]:
        """Reconstruct per-series history from the archived windows.

        Counter families come back as ``{"ts", "rate", "delta"}`` points,
        gauges as ``{"ts", "value"}``, histograms as ``{"ts", "count",
        "rate", "p50", "p99"}`` — one point per archived window, spanning
        every incarnation whose segments survived retention.
        """
        series: dict[str, list[dict[str, Any]]] = {}
        pids: set[int] = set()
        matched = 0
        segs = self.segments()
        for rec in self.records():
            ts = rec.get("ts")
            if not isinstance(ts, (int, float)) or not any(
                    k in rec for k in ("counters", "gauges", "hists")):
                continue  # not a window record (e.g. a shipped fleet tick)
            if (since is not None and ts < since) \
                    or (until is not None and ts > until):
                continue
            elapsed = float(rec.get("elapsed_s") or 0.0)
            matched += 1
            if isinstance(rec.get("pid"), int):
                pids.add(rec["pid"])
            for name, delta in (rec.get("counters") or {}).items():
                if not _matches(name, family, label):
                    continue
                point: dict[str, Any] = {"ts": ts, "delta": delta}
                if elapsed > 0:
                    point["rate"] = round(float(delta) / elapsed, 6)
                series.setdefault(name, []).append(point)
            for name, value in (rec.get("gauges") or {}).items():
                if _matches(name, family, label):
                    series.setdefault(name, []).append(
                        {"ts": ts, "value": value})
            for name, h in (rec.get("hists") or {}).items():
                if not _matches(name, family, label):
                    continue
                le = tuple(float(b) for b in h.get("le", ()))
                counts = tuple(int(c) for c in h.get("counts", ()))
                count = sum(counts)
                point = {"ts": ts, "count": count}
                if elapsed > 0:
                    point["rate"] = round(count / elapsed, 6)
                if count:
                    point["p50"] = metrics.hist_quantile(le, counts, 0.5)
                    point["p99"] = metrics.hist_quantile(le, counts, 0.99)
                series.setdefault(name, []).append(point)
        return {
            "history": 1,
            "archive": str(self.root),
            "segments": len(segs),
            "records": matched,
            "incarnations": len(pids),
            "series": series,
        }

    def profiles(self, since: float | None = None,
                 until: float | None = None,
                 plane: str | None = None) -> list[dict[str, Any]]:
        """The archived profile windows (``kind="profile"`` records the
        flusher drained from the sampler), in segment order — spanning
        every incarnation whose segments survived retention, same as
        :meth:`history`. These records carry ``stacks`` instead of
        counters/gauges/hists, so :meth:`history` skips them and this is
        their dedicated reader (``tools/profile_report.py --archive``)."""
        out: list[dict[str, Any]] = []
        for rec in self.records():
            if rec.get("kind") != "profile":
                continue
            ts = rec.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            if (since is not None and ts < since) \
                    or (until is not None and ts > until):
                continue
            if plane is not None and rec.get("plane") != plane:
                continue
            out.append(rec)
        return out

    def describe(self) -> dict[str, Any]:
        segs = self.segments()
        total = 0
        for seg in segs:
            try:
                total += seg.stat().st_size
            except OSError:
                pass
        with self._lock:
            written = self.records_written
            evicted = self.segments_evicted
            sources = sorted(self._sources)
        return {
            "archive": str(self.root),
            "segments": len(segs),
            "bytes": total,
            "retain_bytes": self.retain_bytes,
            "retain_s": self.retain_s,
            "records_written": written,
            "segments_evicted": evicted,
            "sources": sources,
        }


def _window_record(source: str, prev: dict[str, Any],
                   cur: dict[str, Any]) -> dict[str, Any] | None:
    """One compact on-disk record for the window ``prev → cur``.

    Reset-safe the same way the ring's windowed views are: a counter or
    bucket that shrank (process restart behind a stable name) treats the
    old value as zero rather than producing a negative delta.
    """
    elapsed = float(cur["ts"]) - float(prev["ts"])
    if elapsed <= 0:
        return None
    counters: dict[str, float] = {}
    for name, value in cur["counters"].items():
        if name in _SELF_FAMILIES:
            continue
        old = float(prev["counters"].get(name, 0.0))
        if float(value) < old:
            old = 0.0
        delta = float(value) - old
        if delta:
            counters[name] = round(delta, 6)
    hists: dict[str, dict[str, Any]] = {}
    for name, (le, counts, hsum) in cur["hists"].items():
        old_h = prev["hists"].get(name)
        if (old_h is None or len(old_h[1]) != len(counts)
                or any(int(n) < int(o)
                       for n, o in zip(counts, old_h[1]))):
            old_counts: tuple[int, ...] = (0,) * len(counts)
            old_sum = 0.0
        else:
            old_counts, old_sum = tuple(old_h[1]), float(old_h[2])
        deltas = [int(n) - int(o) for n, o in zip(counts, old_counts)]
        if sum(deltas):
            hists[name] = {
                "le": list(le),
                "counts": deltas,
                "sum": round(max(0.0, float(hsum) - old_sum), 6),
            }
    rec: dict[str, Any] = {
        "ts": cur["wall"],
        "elapsed_s": round(elapsed, 3),
        "source": source,
        "pid": os.getpid(),
    }
    # gauges are last-value: record only CHANGES, so a steady gauge does
    # not keep every otherwise-quiet window alive on disk
    gauges = {name: value for name, value in cur["gauges"].items()
              if prev["gauges"].get(name) != value}
    if counters:
        rec["counters"] = counters
    if gauges:
        rec["gauges"] = gauges
    if hists:
        rec["hists"] = hists
    if len(rec) == 4:
        return None  # quiet window — nothing moved, nothing to keep
    return rec


# ------------------------------------------------------------- registry
_registry_lock = threading.Lock()
_archive: TelemetryArchive | None = None


def current() -> TelemetryArchive | None:
    """The process archive, if :func:`ensure` started one (the history
    endpoint's sys.modules peek lands here)."""
    with _registry_lock:
        return _archive


def ensure(proxy: Any | None = None) -> TelemetryArchive | None:
    """Idempotently start the process archive from
    ``DEMODEL_TELEMETRY_ARCHIVE`` (None — and no side effects — when the
    knob is unset). Attaches the hub ring always and the native mirror
    when ``proxy`` is given; a later call with a proxy upgrades an
    archive started without one."""
    global _archive
    root = telemetry_archive_dir()
    if not root:
        with _registry_lock:
            return _archive
    with _registry_lock:
        if _archive is None or str(_archive.root) != str(Path(root)):
            _archive = TelemetryArchive(Path(root))
            _archive.attach("hub", metrics.HUB.telemetry())
            _archive.start()  # demodel: allow(no-blocking-io-under-lock) — start() only spawns the daemon flusher; the open() the chain reaches runs on THAT thread under the archive's own lock, not under _registry_lock
        archive = _archive
    if proxy is not None:
        archive.attach_native(proxy)
    return archive


def _reset_for_tests() -> None:
    """Stop and forget the process archive (test isolation only)."""
    global _archive
    with _registry_lock:
        archive, _archive = _archive, None
    if archive is not None:
        archive.stop()
