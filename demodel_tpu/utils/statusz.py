"""Live-node introspection: the ``/debug/statusz`` JSON document.

A production node must answer "what are you doing RIGHT NOW" from curl,
without a restart and without pre-enabled tracing: which peer is the
breaker punishing, what is the ByteBudget charged with, which spans are
open (and for how long), and what the flight recorder holds. This module
assembles that document from the places the state already lives —
:mod:`demodel_tpu.utils.faults` (breakers), :mod:`demodel_tpu.utils.trace`
(in-flight spans + recorder), :mod:`demodel_tpu.sink.streaming`
(budgets) — and the servers (Python restore server, native proxy via its
own C++ twin) expose it at ``GET /debug/statusz``.

Deliberately lazy about heavyweight subsystems: a subsystem that was
never imported has no live state worth reporting, so this module reads
``sys.modules`` instead of importing — a dep-light serve node stays
dep-light, and a statusz scrape never triggers a multi-second jax import.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any

from demodel_tpu.utils import metrics, trace

#: process start, for the uptime field (module import is close enough —
#: statusz is assembled lazily, but utils.metrics/trace load at bring-up)
_START_MONOTONIC = time.monotonic()
_START_WALL = time.time()

SCHEMA_VERSION = 1


def _breakers() -> dict[str, dict[str, Any]]:
    faults = sys.modules.get("demodel_tpu.utils.faults")
    if faults is None:
        return {}
    health = faults.PeerHealth._shared  # noqa: SLF001 — read-only peek:
    # shared() would CREATE the registry; statusz must observe, not allocate
    if health is None:
        return {}
    out: dict[str, dict[str, Any]] = health.describe()
    return out


def _budgets() -> list[dict[str, Any]]:
    streaming = sys.modules.get("demodel_tpu.sink.streaming")
    if streaming is None:
        return []
    out: list[dict[str, Any]] = streaming.budgets_snapshot()
    return out


def _swarm() -> list[dict[str, Any]]:
    """Live swarm chunk progress (boards registered by any in-process
    SwarmScheduler) — the per-host half of the pod-scale swarm debugging
    story; ``tools/statusz.py --fleet`` joins these across hosts."""
    placement = sys.modules.get("demodel_tpu.parallel.placement")
    if placement is None:
        return []
    out: list[dict[str, Any]] = placement.boards_snapshot()
    return out


def _gossip() -> dict[str, Any]:
    peer = sys.modules.get("demodel_tpu.parallel.peer")
    if peer is None:
        return {}
    gossip = peer.PeerGossip._shared  # noqa: SLF001 — read-only peek:
    # shared() would CREATE the registry; statusz must observe, not allocate
    if gossip is None:
        return {}
    out: dict[str, Any] = gossip.describe()
    return out


def snapshot(extra: dict[str, Any] | None = None) -> dict[str, Any]:
    """The statusz document. ``extra`` lets a server add its own section
    (registered models, bind address) without forking the schema."""
    recorder = trace.recorder()
    doc: dict[str, Any] = {
        "statusz": SCHEMA_VERSION,
        "pid": os.getpid(),
        "time": time.time(),
        "uptime_sec": round(time.monotonic() - _START_MONOTONIC, 3),
        "start_time": _START_WALL,
        "trace": {
            "mode": trace.mode(),
            "buffer_spans": len(trace.buffer()),
            "recorder_spans": len(recorder),
            "recorder_dropped": recorder.dropped,
            "last_dump": trace._get_state().last_dump,  # noqa: SLF001 —
            # the one writer of this field is dump_recorder in the same
            # package; exposing a public accessor for one read is noise
        },
        "inflight_spans": trace.inflight_tree(),
        "breakers": _breakers(),
        "budgets": _budgets(),
        "swarm": _swarm(),
        "gossip": _gossip(),
        "counters": metrics.HUB.snapshot(),
        "gauges": metrics.HUB.gauges(),
    }
    if extra:
        doc.update(extra)
    return doc
