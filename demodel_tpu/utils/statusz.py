"""Live-node introspection: the ``/debug/statusz`` JSON document.

A production node must answer "what are you doing RIGHT NOW" from curl,
without a restart and without pre-enabled tracing: which peer is the
breaker punishing, what is the ByteBudget charged with, which spans are
open (and for how long), and what the flight recorder holds. This module
assembles that document from the places the state already lives —
:mod:`demodel_tpu.utils.faults` (breakers), :mod:`demodel_tpu.utils.trace`
(in-flight spans + recorder), :mod:`demodel_tpu.sink.streaming`
(budgets) — and the servers (Python restore server, native proxy via its
own C++ twin) expose it at ``GET /debug/statusz``.

Deliberately lazy about heavyweight subsystems: a subsystem that was
never imported has no live state worth reporting, so this module reads
``sys.modules`` instead of importing — a dep-light serve node stays
dep-light, and a statusz scrape never triggers a multi-second jax import.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any

from demodel_tpu.utils import metrics, trace

#: process start, for the uptime field (module import is close enough —
#: statusz is assembled lazily, but utils.metrics/trace load at bring-up)
_START_MONOTONIC = time.monotonic()
_START_WALL = time.time()

#: v2 added the ``tiers`` section (RAM/disk occupancy, budgets, in-flight
#: single-flight leaders) on both planes; v3 added the ``storage``
#: section (degraded read-through state, quarantine/scrub counters); v4
#: added the ``generation`` section (the token-serving plane: running/
#: waiting sequences, KV pool occupancy, admission accounting)
SCHEMA_VERSION = 4


def _breakers() -> dict[str, dict[str, Any]]:
    faults = sys.modules.get("demodel_tpu.utils.faults")
    if faults is None:
        return {}
    health = faults.PeerHealth._shared  # noqa: SLF001 — read-only peek:
    # shared() would CREATE the registry; statusz must observe, not allocate
    if health is None:
        return {}
    out: dict[str, dict[str, Any]] = health.describe()
    return out


def _budgets() -> list[dict[str, Any]]:
    streaming = sys.modules.get("demodel_tpu.sink.streaming")
    if streaming is None:
        return []
    out: list[dict[str, Any]] = streaming.budgets_snapshot()
    return out


def _swarm() -> list[dict[str, Any]]:
    """Live swarm chunk progress (boards registered by any in-process
    SwarmScheduler) — the per-host half of the pod-scale swarm debugging
    story; ``tools/statusz.py --fleet`` joins these across hosts."""
    placement = sys.modules.get("demodel_tpu.parallel.placement")
    if placement is None:
        return []
    out: list[dict[str, Any]] = placement.boards_snapshot()
    return out


def _tiers() -> list[dict[str, Any]]:
    """Live tiered-store state (RAM/disk occupancy vs budget, in-flight
    single-flight leaders) for every TieredStore this process holds —
    the Python half of the section the native proxy composes from its
    hot_stats."""
    tier = sys.modules.get("demodel_tpu.tier")
    if tier is None:
        return []
    out: list[dict[str, Any]] = tier.tiers_snapshot()
    return out


def _storage() -> dict[str, Any]:
    """Storage-fault plane state: per-TieredStore degraded read-through
    flags and quarantine/scrub counters, plus live background scrubbers
    (``sys.modules`` peeks — a scrape never allocates the singletons;
    the native proxy composes its own twin of this section)."""
    out: dict[str, Any] = {}
    tier = sys.modules.get("demodel_tpu.tier")
    if tier is not None:
        rows = []
        for t in tier.tiers_snapshot():
            storage = t.get("storage")
            if storage:
                rows.append({"name": t.get("name"), **storage})
        if rows:
            out["tiers"] = rows
    scrub = sys.modules.get("demodel_tpu.scrub")
    if scrub is not None:
        out["scrubbers"] = scrub.snapshot()
    return out


def _generation() -> dict[str, Any]:
    """Token-serving plane state: the installed engine's running/waiting
    sequences, token counters, admission accounting, and KV pool
    occupancy next to its budget (``sys.modules`` peek — a node that
    never booted an engine reports an empty section and never pays the
    serve plane's jax import)."""
    serve = sys.modules.get("demodel_tpu.serve")
    if serve is None:
        return {}
    engine = serve.current()
    if engine is None:
        return {}
    out: dict[str, Any] = engine.describe()
    return out


def _gossip() -> dict[str, Any]:
    peer = sys.modules.get("demodel_tpu.parallel.peer")
    if peer is None:
        return {}
    gossip = peer.PeerGossip._shared  # noqa: SLF001 — read-only peek:
    # shared() would CREATE the registry; statusz must observe, not allocate
    if gossip is None:
        return {}
    out: dict[str, Any] = gossip.describe()
    return out


def _active_tuner() -> Any:
    """The live adaptive-pull tuner, if one is running (``sys.modules``
    peek — never allocates; a scrape must observe the tuner registry,
    not create it)."""
    tuner = sys.modules.get("demodel_tpu.sink.tuner")
    if tuner is None:
        return None
    return tuner.current()


#: the tunable knobs every plane reports effectively-resolved — "what is
#: this node actually running with" must never require reading env docs.
#: Every value resolves through a shared resolver (never a copied
#: literal, which silently drifts the moment the owner changes — exactly
#: the FILL_TIMEOUT 15-vs-60 doc bug PR 8 had to fix) living in a
#: jax-free module: placement for the swarm knobs, utils.env for the
#: pull-plane knobs (importing parallel.peer or sink.tuner would run
#: their packages' __init__ and drag jax into a dep-light scrape).
def _knob_rows() -> list[tuple[str, Any]]:
    from demodel_tpu.utils import env, faults
    from demodel_tpu.utils.env import (
        default_peer_streams,
        default_pull_window_mb,
        env_int,
        tuner_enabled,
    )
    from demodel_tpu.utils.metrics import _telemetry_ring_cap

    return [
        ("DEMODEL_PEER_STREAMS", default_peer_streams()),
        ("DEMODEL_SINK_PREFETCH",
         # the unset default is backend-dependent (resolved at pull time
         # in sink.remote) — report "auto" instead of importing jax here
         env_int("DEMODEL_SINK_PREFETCH", -1, minimum=0)
         if os.environ.get("DEMODEL_SINK_PREFETCH", "").strip()
         else "auto"),
        ("DEMODEL_PULL_WINDOW_MB", default_pull_window_mb()),
        ("DEMODEL_SINK_BUFFER_MB",
         # the one literal left: the owner (sink.streaming) resolves it
         # inline and is numpy-heavy — keep the default in sync
         env_int("DEMODEL_SINK_BUFFER_MB", 1024, minimum=1)),
        ("DEMODEL_RETRY_MAX", faults._default_max_attempts()),
        ("DEMODEL_RETRY_DEADLINE", int(faults._default_deadline())),
        ("DEMODEL_BREAKER_THRESHOLD", faults.default_breaker_threshold()),
        ("DEMODEL_BREAKER_COOLDOWN",
         int(faults.default_breaker_cooldown())),
        ("DEMODEL_SWARM_CHUNK_MB", env.default_swarm_chunk_mb()),
        ("DEMODEL_SWARM_FILL_TIMEOUT",
         int(env.default_swarm_fill_timeout())),
        ("DEMODEL_SWARM_ORIGIN_STREAMS",
         env.default_swarm_origin_streams()),
        ("DEMODEL_SWARM_REAP", env.swarm_reap_enabled()),
        ("DEMODEL_TIER_RAM_MB", env.default_tier_ram_mb()),
        ("DEMODEL_CACHE_MAX_GB", env.cache_max_gb()),
        ("DEMODEL_TUNER", tuner_enabled()),
        ("DEMODEL_TELEMETRY_RING", _telemetry_ring_cap()),
        ("DEMODEL_TELEMETRY_ARCHIVE", env.telemetry_archive_dir() or "off"),
        ("DEMODEL_TELEMETRY_RETAIN_MB", env.telemetry_retain_mb()),
        ("DEMODEL_TELEMETRY_RETAIN_HOURS", env.telemetry_retain_hours()),
        ("DEMODEL_PROFILE_HZ", env.profile_hz()),
        ("DEMODEL_PROFILE_MAX_STACKS", env.profile_max_stacks()),
        ("DEMODEL_PROFILE_WINDOW_S", env.profile_window_s()),
        ("DEMODEL_STORE_REPROBE_SECS", env.store_reprobe_secs()),
        ("DEMODEL_SCRUB_INTERVAL_SECS", env.scrub_interval_secs()),
        ("DEMODEL_SCRUB_RATE_MB_S", env.scrub_rate_mb_s()),
        ("DEMODEL_GEN_BLOCK", env.gen_block_tokens()),
        ("DEMODEL_GEN_KV_MB", env.gen_kv_mb()),
        ("DEMODEL_GEN_MAX_BATCH", env.gen_max_batch()),
        ("DEMODEL_GEN_QUEUE", env.gen_queue_limit()),
        ("DEMODEL_GEN_RETRY_AFTER", env.gen_retry_after_s()),
        ("DEMODEL_GEN_MAX_NEW", env.gen_max_new_tokens()),
    ]


#: env knob → the live tuner attribute that may be overriding it
_TUNED_KNOBS = {
    "DEMODEL_PEER_STREAMS": "streams",
    "DEMODEL_PULL_WINDOW_MB": "window_mb",
    "DEMODEL_SINK_PREFETCH": "prefetch_depth",
}


def effective_config() -> dict[str, dict[str, Any]]:
    """Each tunable knob's EFFECTIVE value and where it came from:
    ``tuner`` (a live adaptive tuner is overriding it), ``env`` (the
    operator pinned it), or ``default``."""
    tuner = _active_tuner()
    # ONE consistent read of the live tuner state: snapshot() serializes
    # with the tick thread's writes — per-attribute getattr reads could
    # mix two adjacent decisions' knob values in one config document
    snap: dict[str, Any] = tuner.snapshot() if tuner is not None else {}
    out: dict[str, dict[str, Any]] = {}
    for env_var, resolved in _knob_rows():
        source = "env" if os.environ.get(env_var, "").strip() else "default"
        value: Any = resolved
        attr = _TUNED_KNOBS.get(env_var)
        if attr is not None and attr in snap:
            value, source = snap[attr], "tuner"
        out[env_var] = {"value": value, "source": source}
    return out


def _profiler() -> dict[str, Any] | None:
    """The continuous profiler's live counters (sys.modules peek — a
    scrape must never be what starts the sampler thread)."""
    prof = sys.modules.get("demodel_tpu.utils.profiler")
    if prof is None:
        return None
    out: dict[str, Any] | None = prof.describe()
    return out


def _telemetry_summary() -> dict[str, Any]:
    """The statusz-sized slice of the telemetry plane: windowed p99s per
    histogram family plus per-series counter rates with their labels
    intact — the fleet per-peer table joins breaker states against these
    (the full document lives at ``/debug/telemetry``)."""
    tel = metrics.HUB.telemetry().summary()
    return {
        "snapshots": tel["snapshots"],
        "windows_s": tel["windows_s"],
        "p99": {
            name: {w: windows[w]["p99"] for w in windows}
            for name, windows in tel["hist"].items()
        },
        "rates": tel["rates"],
    }


def snapshot(extra: dict[str, Any] | None = None) -> dict[str, Any]:
    """The statusz document. ``extra`` lets a server add its own section
    (registered models, bind address) without forking the schema."""
    recorder = trace.recorder()
    doc: dict[str, Any] = {
        "statusz": SCHEMA_VERSION,
        "pid": os.getpid(),
        "time": time.time(),
        "uptime_sec": round(time.monotonic() - _START_MONOTONIC, 3),
        "start_time": _START_WALL,
        "trace": {
            "mode": trace.mode(),
            "buffer_spans": len(trace.buffer()),
            "recorder_spans": len(recorder),
            "recorder_dropped": recorder.dropped,
            "last_dump": trace._get_state().last_dump,  # noqa: SLF001 —
            # the one writer of this field is dump_recorder in the same
            # package; exposing a public accessor for one read is noise
        },
        "inflight_spans": trace.inflight_tree(),
        "breakers": _breakers(),
        "budgets": _budgets(),
        "swarm": _swarm(),
        "tiers": _tiers(),
        "storage": _storage(),
        "generation": _generation(),
        "gossip": _gossip(),
        "config": effective_config(),
        "profiler": _profiler(),
        "telemetry": _telemetry_summary(),
        "counters": metrics.HUB.snapshot(),
        "gauges": metrics.HUB.gauges(),
    }
    if extra:
        doc.update(extra)
    return doc
