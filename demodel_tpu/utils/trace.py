"""Dependency-free distributed tracing for the pull/serve/restore planes.

The reference ships exactly one observability primitive — a response hook
that prints (``cmd/demodel/start.go:201-204``, SURVEY.md §5) — and the
rebuild's Prometheus counters (PR 2/4) say *that* a pull stalled, never
*where*. This module answers "where did the 30 s go": budget wait? breaker
cooldown? window retry? peer stream?

Design, smallest-thing-that-works:

- :class:`Span` — monotonic-clock timed, with attributes, timestamped
  events (retry attempts, breaker transitions, failovers) and an error
  status. Spans nest through ``contextvars`` so the ambient parent flows
  through ``await`` points for free; :func:`wrap` captures the ambient
  context for callables handed to thread pools (``contextvars`` does NOT
  cross ``threading`` boundaries on its own).
- :class:`TraceBuffer` — process-wide bounded ring of finished spans
  (``DEMODEL_TRACE_BUFFER``, default 8192); the Chrome exporter and tests
  read it back.
- exporters — ``DEMODEL_TRACE=/path`` appends one JSON object per finished
  span (the JSONL contract ``tools/trace_report.py`` consumes);
  :func:`dump_chrome` / :func:`chrome_events` emit Chrome trace-event JSON
  that loads in Perfetto (``ui.perfetto.dev``) / ``chrome://tracing``.
- wire propagation — :func:`traceparent` / :func:`parse_traceparent`
  implement the W3C header; the client side injects it at the
  ``request_with_retry`` choke point (and the raw streaming GETs in
  ``sink/remote`` / ``parallel/peer``), servers extract it and start a
  child span, so a multi-host pull stitches into ONE trace.
- span-duration summaries feed the existing metrics exposition:
  ``trace_spans_total{span=...}`` / ``trace_span_seconds_total{span=...}``.

Observability has THREE tiers (the live-ops rebuild):

- **export** (``DEMODEL_TRACE=/path`` or :func:`enable`): everything below
  plus the JSONL sink and the export :class:`TraceBuffer`.
- **observe** (the DEFAULT): spans run and feed (a) the per-stage latency
  histograms on the metrics scrape (``stage_duration_seconds{span=...}``
  — every named span observes its duration on finish, no per-site
  instrumentation), (b) the always-on **flight recorder** — a small
  bounded ring of recently completed spans, separate from the export
  buffer, dumped to disk on ``SIGUSR2`` and automatically when a ROOT
  span finishes with error status — and (c) the **in-flight registry**
  every live span sits in until it finishes, so ``/debug/statusz`` can
  print what a stuck pull is doing *right now*. Nothing is exported.
- **off** (``DEMODEL_OBS=0``): :func:`span` returns a shared no-op
  context manager after one module-global check — no allocation, no
  clock read — guarded by a microbenchmark in ``tests/test_trace.py``.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import tempfile
import threading
import time
from collections import deque
from typing import IO, Any, Callable

#: ambient parent span (crosses asyncio awaits for free; for threads use
#: :func:`wrap` at the submit site)
_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "demodel_trace_span", default=None)

_TRACEPARENT_VERSION = "00"
_SAMPLED = "01"


def _hex(nbytes: int) -> str:
    return "%0*x" % (nbytes * 2, random.getrandbits(nbytes * 8))


# ------------------------------------------------------------------ state


def _env_off(name: str) -> bool:
    """True when ``name`` is explicitly disabled (``0/false/off/no``)."""
    return os.environ.get(name, "").strip().lower() in (
        "0", "false", "off", "no")


class _State:
    """Resolved-from-env exporter state. Rebuilt by :func:`reset`."""

    def __init__(self) -> None:
        path = os.environ.get("DEMODEL_TRACE", "").strip()
        self.enabled = bool(path) or _FORCED
        #: observe tier: spans run (recorder + histograms + in-flight
        #: registry) even with no exporter configured. DEMODEL_OBS=0 is
        #: the full kill switch — span() then returns the shared no-op.
        self.observing = not _env_off("DEMODEL_OBS")
        self.jsonl_path = path or None
        self.sample = _sample_rate()
        self.buffer = TraceBuffer(_buffer_cap())
        #: the flight recorder: always-on bounded ring of recently
        #: COMPLETED spans, separate from the export buffer — the
        #: post-mortem a fault leaves behind without pre-enabled tracing
        self.recorder = TraceBuffer(_recorder_cap())
        self.recorder_dir = os.environ.get(
            "DEMODEL_RECORDER_DIR", "").strip() or tempfile.gettempdir()
        self.autodump = not _env_off("DEMODEL_RECORDER_AUTODUMP")
        self.autodump_min_s = _autodump_min_s()
        self.last_dump: str | None = None
        self._dump_lock = threading.Lock()
        self._dump_seq = 0
        self._last_autodump = 0.0
        self._sink_lock = threading.Lock()
        self._sink: IO[str] | None = None  # lazily opened JSONL file

    def export(self, rec: dict[str, Any]) -> None:
        self.buffer.add(rec)
        if self.jsonl_path is None:
            return
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        try:
            with self._sink_lock:
                if self._sink is None:
                    # demodel: allow(no-blocking-io-under-lock) —
                    # single-flight by design: this lock exists ONLY to
                    # serialize appends to the one trace sink (interleaved
                    # JSONL lines would corrupt the file); nothing else
                    # ever waits on it
                    self._sink = open(  # noqa: SIM115 — process lifetime
                        self.jsonl_path, "a", encoding="utf-8")
                self._sink.write(line)
                self._sink.flush()
        except OSError as e:
            # tracing must never take the plane down: disable the sink,
            # keep the in-memory buffer
            self.jsonl_path = None
            _log().warning("trace sink unusable (%s); JSONL export off", e)


def _buffer_cap() -> int:
    from demodel_tpu.utils.env import env_int

    return env_int("DEMODEL_TRACE_BUFFER", 8192, minimum=16)


def _recorder_cap() -> int:
    from demodel_tpu.utils.env import env_int

    return env_int("DEMODEL_RECORDER_CAP", 512, minimum=16)


def _autodump_min_s() -> float:
    """Rate limit between automatic error-root dumps (seconds; 0 = every
    error root dumps — tests). A fault storm must leave ONE post-mortem
    per window, not grind the disk with one file per failed window."""
    raw = os.environ.get("DEMODEL_RECORDER_MIN_S", "").strip()
    if not raw:
        return 60.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 60.0


def _sample_rate() -> float:
    """``DEMODEL_TRACE_SAMPLE`` ∈ [0, 1]: head-sampling probability for new
    ROOT spans (default 1.0 — record everything). Multi-user serve traffic
    sets e.g. ``0.01`` so tracing overhead/volume scales with the sample,
    not the load. EXPORT-only: a sampled-out trace skips the JSONL sink and
    export buffer, but its spans still run — the flight recorder, statusz
    in-flight view and latency histograms are always-on by contract and
    must not go dark because an export knob was tuned. Malformed values
    degrade to 1.0, same policy as env_int."""
    raw = os.environ.get("DEMODEL_TRACE_SAMPLE", "").strip()
    if not raw:
        return 1.0
    try:
        rate = float(raw)
    except ValueError:
        _log().warning("DEMODEL_TRACE_SAMPLE=%r is not a float; sampling "
                       "everything", raw)
        return 1.0
    return min(1.0, max(0.0, rate))


def _log() -> logging.Logger:
    from demodel_tpu.utils.logging import get_logger

    return get_logger("trace")


_FORCED = False           # enable() without an env var (tests/CLI)
_state: _State | None = None
_state_lock = threading.Lock()


def _get_state() -> _State:
    global _state
    st = _state
    if st is None:
        with _state_lock:
            st = _state
            if st is None:
                st = _state = _State()
        _install_recorder_signal()
    return st


def enabled() -> bool:
    """Full EXPORT tracing on (JSONL sink / export buffer)."""
    st = _state
    return st.enabled if st is not None else _get_state().enabled


def active() -> bool:
    """Spans run at all (export OR the default observe tier). The guard
    for call sites that pay real work building span attributes."""
    st = _state
    if st is None:
        st = _get_state()
    return st.enabled or st.observing


def mode() -> str:
    """``"export"`` / ``"observe"`` / ``"off"`` — for /debug/statusz."""
    st = _get_state()
    if st.enabled:
        return "export"
    return "observe" if st.observing else "off"


def enable(jsonl_path: str | None = None) -> None:
    """Force tracing on (tests / CLI), optionally with a JSONL sink."""
    global _FORCED, _state
    with _state_lock:
        _FORCED = True
        if jsonl_path is not None:
            os.environ["DEMODEL_TRACE"] = jsonl_path
        _state = None
    _get_state()


def reset() -> None:
    """Drop exporter state and re-read the env (tests; cheap). Clears the
    in-flight registry too — spans left open by a failed test must not
    haunt the next test's statusz snapshot."""
    global _FORCED, _state
    with _state_lock:
        _FORCED = False
        _state = None
    with _inflight_lock:
        _inflight.clear()


# ----------------------------------------------------------------- buffer


class TraceBuffer:
    """Bounded ring of finished-span records (dicts, newest last)."""

    def __init__(self, cap: int) -> None:
        self.cap = cap
        self._lock = threading.Lock()
        self._spans: deque[dict[str, Any]] = deque(maxlen=cap)
        self.dropped = 0

    def add(self, rec: dict[str, Any]) -> None:
        with self._lock:
            if len(self._spans) == self.cap:
                self.dropped += 1
            self._spans.append(rec)

    def snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


def buffer() -> TraceBuffer:
    return _get_state().buffer


def recorder() -> TraceBuffer:
    """The flight-recorder ring (completed spans, always on under the
    observe tier)."""
    return _get_state().recorder


# -------------------------------------------------- in-flight span registry

#: every live (entered-but-unfinished) Span, keyed by id() — what
#: /debug/statusz prints when you ask a stuck node what it is doing NOW
_inflight_lock = threading.Lock()
_inflight: dict[int, "Span"] = {}


def inflight() -> list[dict[str, Any]]:
    """Flat snapshot of every currently-open span: name, ids, age (secs
    since start), live attrs, thread. Newest-last by age."""
    with _inflight_lock:
        spans = list(_inflight.values())
    now = time.perf_counter()
    out = []
    for s in spans:
        if s.dur is not None:
            continue  # finished between snapshot and render
        out.append({
            "name": s.name,
            "trace": s.trace_id,
            "span": s.span_id,
            "parent": s.parent_id,
            "age_sec": round(max(0.0, now - s._t0), 6),
            "thread": s._thread_name,
            **({"attrs": dict(s.attrs)} if s.attrs else {}),
        })
    out.sort(key=lambda r: -float(r["age_sec"]))
    return out


def nest_spans(flat: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Flat span dicts (``span``/``parent`` keys) → trees: every span
    whose parent is not in the set (remote or already-finished parents
    both root a local tree) becomes a root, descendants nest under
    ``children``. Shared by :func:`inflight_tree` and the recorder-dump
    renderer in ``tools/statusz.py``."""
    by_id = {r["span"]: dict(r, children=[]) for r in flat if "span" in r}
    roots: list[dict[str, Any]] = []
    for r in by_id.values():
        parent = r.get("parent")
        if parent is not None and parent in by_id:
            by_id[parent]["children"].append(r)
        else:
            roots.append(r)
    return roots


def inflight_tree() -> list[dict[str, Any]]:
    """The open spans as trees — the statusz "what is this pull doing
    right now" view."""
    return nest_spans(inflight())


# --------------------------------------------------- flight recorder dumps


def dump_recorder(reason: str, path: str | None = None) -> str:
    """Write the flight recorder (completed-span ring + the in-flight
    span snapshot) as one JSON file; returns the path written. The
    post-mortem artifact: SIGUSR2 and error-status roots both land here,
    and ``tools/statusz.py`` renders it."""
    st = _get_state()
    with st._dump_lock:
        st._dump_seq += 1
        seq = st._dump_seq
    if path is None:
        path = os.path.join(
            st.recorder_dir, f"demodel-flightrec-{os.getpid()}-{seq}.json")
    doc = {
        "kind": "demodel-flight-recorder",
        "reason": reason,
        "ts": time.time(),
        "pid": os.getpid(),
        "dropped": st.recorder.dropped,
        "spans": st.recorder.snapshot(),
        "inflight": inflight(),
    }
    # one signal, spans AND frames: embed the profiler's last rolled
    # window (or its live aggregate) when the profiler plane is loaded.
    # sys.modules peek, same dep-light stance as the statusz sections —
    # a recorder dump must never be the thing that imports the profiler.
    import sys as _sys

    prof = _sys.modules.get("demodel_tpu.utils.profiler")
    if prof is not None:
        try:
            window = prof.recorder_window()
            if window is not None:
                doc["profile"] = window
        except Exception as e:  # noqa: BLE001 — post-mortem must still
            # land even if the profiler misbehaves; record why it is bare
            doc["profile_error"] = str(e)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, separators=(",", ":"), default=str)
    st.last_dump = path
    _log().warning("flight recorder dumped (%s): %s", reason, path)
    return path


def _maybe_autodump(rec: dict[str, Any]) -> None:
    """Error-status ROOT span finished: leave a post-mortem on disk
    (rate-limited) — the first fault in prod must not require a restart
    with tracing pre-enabled to be diagnosable."""
    st = _get_state()
    if not st.autodump:
        return
    now = time.monotonic()
    with st._dump_lock:
        if st._last_autodump and now - st._last_autodump < st.autodump_min_s:
            return
        st._last_autodump = now
    try:
        dump_recorder(f"error-root:{rec['name']}")
    except OSError as e:
        _log().warning("flight-recorder dump failed: %s", e)


_signal_installed = False


def _install_recorder_signal() -> None:
    """SIGUSR2 → flight-recorder dump. Installed once per process, from
    the main thread only, and never over a user-set handler (only the
    default disposition — which would kill the process — is replaced).
    Called at module import (normally the main thread) AND on every state
    (re)build, so a process whose first span ran on a worker thread still
    gets the handler from any later main-thread state rebuild."""
    global _signal_installed
    if _signal_installed or _env_off("DEMODEL_RECORDER_SIGNAL"):
        return
    try:
        import signal

        if threading.current_thread() is not threading.main_thread():
            return  # not installable from here; later main-thread calls try
        if signal.getsignal(signal.SIGUSR2) is not signal.SIG_DFL:
            _signal_installed = True  # someone owns it; never contend
            return

        def _dump_thread() -> None:
            try:
                dump_recorder("sigusr2")
            except OSError as e:
                _log().warning("SIGUSR2 dump failed: %s", e)

        def _on_sigusr2(_signum: int, _frame: Any) -> None:
            # NEVER dump from the handler itself: it runs on the main
            # thread on top of whatever frame the signal preempted — if
            # that frame holds the recorder/inflight/dump lock (any span
            # start/finish does), a direct dump self-deadlocks the node
            # the dump exists to diagnose. A thread just waits its turn.
            threading.Thread(target=_dump_thread, daemon=True,
                             name="demodel-sigusr2-dump").start()

        signal.signal(signal.SIGUSR2, _on_sigusr2)
        _signal_installed = True
    except (ValueError, OSError, AttributeError):  # non-main thread race /
        return  # platforms without SIGUSR2 — the recorder still works


# ------------------------------------------------------------------- Span


class Span:
    """One timed operation. Use via ``with trace.span("window-read", ...):``
    — entering makes it the ambient parent, exiting finishes + exports it.
    An exception propagating through marks ``status=error`` (and records
    the exception type/message) before re-raising."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "events", "status", "error", "_t0", "_wall0", "dur",
                 "_token", "_thread_name", "_thread_ident",
                 "_suppress_export", "_unsampled_token")

    def __init__(self, name: str, trace_id: str, parent_id: str | None,
                 attrs: dict[str, Any] | None,
                 suppress_export: bool = False) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = _hex(8)
        self.parent_id = parent_id
        self.attrs: dict[str, Any] = attrs or {}
        self.events: list[tuple[float, str, dict[str, Any]]] = []
        self.status = "ok"
        self.error: str | None = None
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self.dur: float | None = None
        self._token: contextvars.Token["Span | None"] | None = None
        th = threading.current_thread()
        self._thread_name = th.name
        # starting-thread ident, recorded NOW: the profiler joins samples
        # to the innermost live span per thread, and must not wait for
        # finish() to learn which thread a span runs on
        self._thread_ident = th.ident
        #: head-sampled OUT (export tier only): the span still runs —
        #: recorder/statusz/histograms stay whole — but never exports
        self._suppress_export = suppress_export
        self._unsampled_token: contextvars.Token[bool] | None = None
        # live until finish(): the /debug/statusz in-flight view
        with _inflight_lock:
            _inflight[id(self)] = self

    # -- enrichment ----------------------------------------------------
    def set_attr(self, key: str, value: Any) -> None:
        # copy-on-write: statusz's inflight() snapshots attrs from another
        # thread with no lock — rebinding a fresh dict is atomic, mutating
        # in place would let dict(attrs) race a concurrent insert
        self.attrs = {**self.attrs, key: value}

    def event(self, name: str, **attrs: Any) -> None:
        """Timestamped point event on this span (retry attempt, breaker
        transition, failover) — offset seconds from span start."""
        self.events.append(
            (round(time.perf_counter() - self._t0, 6), name, attrs))

    # -- lifecycle -----------------------------------------------------
    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        if self._suppress_export and not _unsampled.get():
            # mark the context so descendants (and wrap()-crossed thread
            # tasks) inherit the export-drop with this root — whole
            # traces drop from the export, never mid-trace fragments
            self._unsampled_token = _unsampled.set(True)
        return self

    def __exit__(self, exc_type: type[BaseException] | None,
                 exc: BaseException | None, tb: object) -> None:
        if self._unsampled_token is not None:
            _unsampled.reset(self._unsampled_token)
            self._unsampled_token = None
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc is not None:
            self.status = "error"
            self.error = f"{type(exc).__name__}: {exc}"
        self.finish()

    def finish(self) -> None:
        if self.dur is not None:
            return  # idempotent: __exit__ after an explicit finish()
        self.dur = time.perf_counter() - self._t0
        with _inflight_lock:
            _inflight.pop(id(self), None)
        th = threading.current_thread()
        rec: dict[str, Any] = {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "ts": self._wall0,
            "dur": round(self.dur, 6),
            "pid": os.getpid(),
            "tid": th.ident,
            "thread": th.name,
            "status": self.status,
        }
        if self.error is not None:
            rec["error"] = self.error
        if self.attrs:
            rec["attrs"] = self.attrs
        if self.events:
            rec["events"] = [
                {"t": t, "name": n, **({"attrs": a} if a else {})}
                for t, n, a in self.events]
        st = _get_state()
        # the flight recorder sees every finished span (observe tier);
        # the export buffer/JSONL only when full tracing is on AND the
        # root survived head-sampling — sampling is an export-volume
        # knob, never a hole in the always-on surfaces
        st.recorder.add(rec)
        if st.enabled and not self._suppress_export:
            st.export(rec)
        # the tracing→metrics bridge: every named span feeds the per-stage
        # latency histogram + the span summaries on finish, so the scrape
        # shows where pull/serve/restore time goes even with no sink set
        from demodel_tpu.utils import metrics

        metrics.HUB.observe(
            metrics.labeled("stage_duration_seconds", span=self.name),
            self.dur)
        label = metrics.labeled("trace_spans_total", span=self.name)
        metrics.HUB.inc(label)
        metrics.HUB.inc(
            metrics.labeled("trace_span_seconds_total", span=self.name),
            self.dur)
        if self.status == "error" and self.parent_id is None:
            _maybe_autodump(rec)


class _NoopSpan:
    """The disabled-tracing fast path: one shared instance, every method a
    constant-time no-op. ``span()`` returns it after a single module-global
    check — the hot path allocates nothing and never reads a clock."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: object) -> None:
        return None

    def set_attr(self, key: str, value: Any) -> None:
        return None

    def event(self, name: str, **attrs: Any) -> None:
        return None

    def finish(self) -> None:
        return None


NOOP = _NoopSpan()

#: set while inside a head-UNSAMPLED root: descendants (including across
#: :func:`wrap`-captured thread hops) drop from the EXPORT with it, so a
#: sampling decision drops or keeps whole traces, never mid-trace
#: fragments — the observe-tier surfaces (recorder/statusz/histograms)
#: stay whole regardless
_unsampled: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "demodel_trace_unsampled", default=False)


def span(name: str, remote_parent: str | None = None,
         **attrs: Any) -> "Span | _NoopSpan":
    """Start a span under the ambient parent (or a remote ``traceparent``
    header value). Returns :data:`NOOP` when observability is fully off
    (``DEMODEL_OBS=0``); under the default observe tier the span runs but
    only feeds the flight recorder + histograms + in-flight registry.
    With export tracing on, new ROOT spans are head-sampled per
    ``DEMODEL_TRACE_SAMPLE``: a sampled-out root still RUNS (the
    always-on surfaces must not go dark behind an export knob) but its
    whole subtree skips the export buffer/JSONL; spans with a remote
    parent are always exported (the upstream host already decided)."""
    st = _state
    if st is None:
        st = _get_state()
    if not (st.enabled or st.observing):
        return NOOP
    parent_trace: str | None = None
    parent_id: str | None = None
    from_remote = False
    if remote_parent is not None:
        parsed = parse_traceparent(remote_parent)
        if parsed is not None:
            parent_trace, parent_id = parsed
            from_remote = True
    if parent_trace is None:
        cur = _current.get()
        if cur is not None:
            parent_trace, parent_id = cur.trace_id, cur.span_id
    if parent_trace is None:
        # new root: the one head-sampling decision for the whole trace —
        # export-only, and only worth rolling when export is actually on
        suppress = _unsampled.get() or (
            st.enabled and st.sample < 1.0 and random.random() >= st.sample)
    else:
        suppress = not from_remote and _unsampled.get()
    return Span(name, parent_trace or _hex(16), parent_id, attrs or None,
                suppress_export=suppress)


def current() -> Span | None:
    """The ambient span, or None (disabled or outside any span)."""
    return _current.get()


def event(name: str, **attrs: Any) -> None:
    """Attach a point event to the ambient span (no-op without one) —
    how RetryPolicy attempts and breaker transitions land on whichever
    operation triggered them."""
    cur = _current.get()
    if cur is not None:
        cur.event(name, **attrs)


# ------------------------------------------------------------ propagation


def traceparent() -> str | None:
    """W3C ``traceparent`` value for the ambient span, or None."""
    cur = _current.get()
    if cur is None:
        return None
    return (f"{_TRACEPARENT_VERSION}-{cur.trace_id}-{cur.span_id}-"
            f"{_SAMPLED}")


def subtree_suppressed() -> bool:
    """True inside a head-UNSAMPLED (export-dropped) root. Work fanned
    out from here over channels contextvars cannot cross (queues,
    executors without :func:`wrap`) must carry this flag and skip its
    spans, or an export-dropped trace leaks orphan fragments from the
    far side of the channel (remote-parented spans always export)."""
    return _unsampled.get()


def parse_traceparent(value: str) -> tuple[str, str] | None:
    """``(trace_id, parent_span_id)`` from a traceparent header, or None
    for anything malformed (never raises: header input is peer input)."""
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    _ver, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return trace_id, span_id


def inject_headers(headers: dict[str, str] | None) -> dict[str, str] | None:
    """Return ``headers`` with ``traceparent`` added when a span is
    ambient (copies before mutating; None stays None when no span)."""
    tp = traceparent()
    if tp is None:
        return headers
    out = dict(headers or {})
    out.setdefault("traceparent", tp)
    return out


def wrap(fn: Callable[..., Any]) -> Callable[..., Any]:
    """Capture the ambient trace context NOW for a callable that will run
    on another thread (``contextvars`` does not cross ``threading``).
    Identity when tracing is disabled — executor hot paths pay nothing.
    An unsampled-root context is captured too, so a dropped trace's thread
    fan-out doesn't re-roll the sampling dice per task."""
    if not active() or (_current.get() is None and not _unsampled.get()):
        return fn
    ctx = contextvars.copy_context()

    def run(*a: Any, **kw: Any) -> Any:
        return ctx.run(fn, *a, **kw)

    return run


# -------------------------------------------------------- chrome exporter


def chrome_events(records: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Chrome trace-event objects (Perfetto/chrome://tracing) for finished
    span records: one complete ("X") event per span, one instant ("i")
    event per span event. Spans from different hosts of one pull carry
    different pids, so a stitched multi-host trace lays out per-process."""
    out: list[dict[str, Any]] = []
    for r in records:
        ts_us = r["ts"] * 1e6
        args = dict(r.get("attrs") or {})
        args["trace"] = r["trace"]
        args["span"] = r["span"]
        if r.get("parent"):
            args["parent"] = r["parent"]
        if r.get("error"):
            args["error"] = r["error"]
        out.append({
            "name": r["name"],
            "cat": "demodel",
            "ph": "X",
            "ts": ts_us,
            "dur": max(r.get("dur", 0.0), 0.0) * 1e6,
            "pid": r.get("pid", 0),
            "tid": r.get("tid", 0) or 0,
            "args": args,
        })
        for ev in r.get("events", ()):
            out.append({
                "name": f"{r['name']}:{ev['name']}",
                "cat": "demodel",
                "ph": "i",
                "s": "t",
                "ts": ts_us + ev.get("t", 0.0) * 1e6,
                "pid": r.get("pid", 0),
                "tid": r.get("tid", 0) or 0,
                "args": dict(ev.get("attrs") or {}),
            })
    return out


def dump_chrome(path: str,
                records: list[dict[str, Any]] | None = None) -> int:
    """Write a Chrome trace-event JSON file (records default to the
    process buffer). Returns the event count."""
    recs = records if records is not None else buffer().snapshot()
    events = chrome_events(recs)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(events)


# import usually happens on the main thread — grab the SIGUSR2 slot now,
# before any worker thread can be the one to build the first _State
_install_recorder_signal()
