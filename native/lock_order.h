// Compile-time-optional runtime lock-order checking for the data plane.
//
// The Python-side analyzer (tools/analyze, lock-order pass) proves the
// PYTHON lock graph acyclic statically; this shim is the C++ half: with
// -DDM_LOCK_ORDER_CHECK every member mutex of Store/Proxy becomes a
// ranked mutex, and acquiring a lock while holding one of equal or
// higher rank aborts with a diagnostic. The TSan selftest builds with
// the check on (native/Makefile selftest-tsan), so every selftest
// operation doubles as a lock-order assertion run — cycles are caught
// deterministically instead of only when the deadlock interleaving
// happens to fire.
//
// Rank order (low = outermost, must be acquired first):
//   Proxy:  reactor < queue < sessions < fill < leaf < upstream < hint
//           < restore < telemetry < profile < ktls
//   Store:  gc < writers < index < pin < fd < hot
// Proxy locks rank below Store locks because proxy paths call into the
// store while holding their own locks (register_tensor holds restore_mu_
// across Store::pin/unpin), never the reverse.
//
// Deliberately out of scheme (plain std::mutex): FillState::mu (paired
// with a condition_variable — std::condition_variable requires
// std::unique_lock<std::mutex>) and RangeWriter::mu_ (per-writer leaf,
// never held across another acquisition).
#pragma once

#include <mutex>

#ifdef DM_LOCK_ORDER_CHECK
#include <cstdio>
#include <cstdlib>
#endif

namespace dm {

// lock ranks (see ordering rationale above)
constexpr int kRankProxyReactor = 6;
constexpr int kRankProxyQueue = 8;
constexpr int kRankProxySessions = 10;
constexpr int kRankProxyFill = 12;
constexpr int kRankProxyLeaf = 14;
constexpr int kRankProxyUpstream = 16;
constexpr int kRankProxyHint = 18;
constexpr int kRankProxyRestore = 20;
constexpr int kRankProxyTelemetry = 22;  // leaf: held only over ring ops
constexpr int kRankProxyProfile = 24;  // leaf: profiler aggregate only
constexpr int kRankProxyKtls = 26;  // leaf: one-shot kTLS probe cache only
constexpr int kRankProxyFdCache = 27;  // leaf: shared store read-fd refcounts
constexpr int kRankStoreGc = 30;
constexpr int kRankStoreWriters = 32;
constexpr int kRankStoreIndex = 34;
constexpr int kRankStorePin = 36;
constexpr int kRankStoreFd = 38;
constexpr int kRankStoreHot = 40;  // mmap hot tier — innermost leaf

#ifdef DM_LOCK_ORDER_CHECK

// Ranked mutex: lock() asserts the calling thread holds no dm::Mutex of
// equal or higher rank. BasicLockable, so std::lock_guard works.
class OrderedMutex {
 public:
  explicit OrderedMutex(int rank) : rank_(rank) {}
  OrderedMutex(const OrderedMutex &) = delete;
  OrderedMutex &operator=(const OrderedMutex &) = delete;

  void lock() {
    check_order();
    mu_.lock();
    push();
  }

  bool try_lock() {
    // try_lock cannot deadlock, so no order assertion — but the held
    // stack stays honest for later lock() calls
    if (!mu_.try_lock()) return false;
    push();
    return true;
  }

  void unlock() {
    pop();
    mu_.unlock();
  }

 private:
  static constexpr int kMaxHeld = 16;
  static inline thread_local int t_held_[kMaxHeld] = {};
  static inline thread_local int t_depth_ = 0;

  void check_order() const {
    for (int i = 0; i < t_depth_; ++i) {
      if (t_held_[i] >= rank_) {
        ::fprintf(stderr,
                  "[demodel-tpu] lock-order violation: acquiring rank %d "
                  "while holding rank %d (see native/lock_order.h)\n",
                  rank_, t_held_[i]);
        ::abort();
      }
    }
  }

  void push() const {
    if (t_depth_ < kMaxHeld) t_held_[t_depth_] = rank_;
    ++t_depth_;
  }

  void pop() const {
    // unlock order is LIFO under lock_guard scoping, but tolerate
    // out-of-order release: drop the topmost entry matching our rank
    for (int i = (t_depth_ < kMaxHeld ? t_depth_ : kMaxHeld) - 1; i >= 0;
         --i) {
      if (t_held_[i] == rank_) {
        for (int j = i; j + 1 < t_depth_ && j + 1 < kMaxHeld; ++j)
          t_held_[j] = t_held_[j + 1];
        break;
      }
    }
    if (t_depth_ > 0) --t_depth_;
  }

  const int rank_;
  std::mutex mu_;
};

using Mutex = OrderedMutex;

#else  // !DM_LOCK_ORDER_CHECK

// Zero-cost default: a std::mutex that swallows the rank argument.
struct Mutex : std::mutex {
  Mutex() = default;
  explicit Mutex(int /*rank*/) {}
};

#endif  // DM_LOCK_ORDER_CHECK

}  // namespace dm
