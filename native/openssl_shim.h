// dlopen-based OpenSSL 3 binding.
//
// This image ships libssl.so.3 / libcrypto.so.3 at runtime but neither the
// dev headers nor the .so linker symlinks, so the data plane declares the
// minimal TLS surface itself and binds symbols on first use. Call sites use
// the standard OpenSSL names (SSL_read, SSL_CTX_new, ...) — each name is a
// macro over a bound function pointer, so the code body reads like normal
// OpenSSL and would compile against real headers unchanged.
#pragma once

#include <dlfcn.h>

#include <cstddef>
#include <cstdio>
#include <cstdlib>

extern "C" {
typedef struct dm_ssl_ctx_st SSL_CTX;
typedef struct dm_ssl_st SSL;
typedef struct dm_ssl_method_st SSL_METHOD;
typedef struct dm_x509_vfy_param_st X509_VERIFY_PARAM;
}

// constants (stable OpenSSL ABI values; DM_ prefix because the real macros
// live in headers we don't have)
#define DM_SSL_FILETYPE_PEM 1
#define DM_SSL_VERIFY_PEER 0x01
#define DM_SSL_ERROR_WANT_READ 2
#define DM_SSL_ERROR_WANT_WRITE 3
#define DM_SSL_ERROR_ZERO_RETURN 6
#define DM_SSL_CTRL_SET_TLSEXT_HOSTNAME 55
#define DM_TLSEXT_NAMETYPE_host_name 0
// kTLS surface (OpenSSL 3.x ABI values): SSL_OP_ENABLE_KTLS is
// SSL_OP_BIT(3); the BIO ctrl asks whether the write BIO actually
// offloaded to the kernel after the handshake; SSL_CTRL_MODE arms
// partial/moving-buffer writes for the non-blocking SSL_write pump.
#define DM_SSL_OP_ENABLE_KTLS 0x8ul
#define DM_BIO_CTRL_GET_KTLS_SEND 73
#define DM_SSL_CTRL_MODE 33
#define DM_SSL_MODE_ENABLE_PARTIAL_WRITE 0x1l
#define DM_SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER 0x2l

namespace dm_ssl {

struct Api {
  const SSL_METHOD *(*TLS_server_method_)(void);
  const SSL_METHOD *(*TLS_client_method_)(void);
  SSL_CTX *(*SSL_CTX_new_)(const SSL_METHOD *);
  void (*SSL_CTX_free_)(SSL_CTX *);
  int (*SSL_CTX_use_certificate_chain_file_)(SSL_CTX *, const char *);
  int (*SSL_CTX_use_PrivateKey_file_)(SSL_CTX *, const char *, int);
  int (*SSL_CTX_check_private_key_)(const SSL_CTX *);
  int (*SSL_CTX_set_default_verify_paths_)(SSL_CTX *);
  int (*SSL_CTX_load_verify_locations_)(SSL_CTX *, const char *, const char *);
  void (*SSL_CTX_set_verify_)(SSL_CTX *, int, void *);
  SSL *(*SSL_new_)(SSL_CTX *);
  void (*SSL_free_)(SSL *);
  int (*SSL_set_fd_)(SSL *, int);
  int (*SSL_accept_)(SSL *);
  int (*SSL_connect_)(SSL *);
  int (*SSL_read_)(SSL *, void *, int);
  int (*SSL_pending_)(const SSL *);
  int (*SSL_has_pending_)(const SSL *);
  int (*SSL_write_)(SSL *, const void *, int);
  int (*SSL_shutdown_)(SSL *);
  int (*SSL_get_error_)(const SSL *, int);
  long (*SSL_ctrl_)(SSL *, int, long, void *);
  X509_VERIFY_PARAM *(*SSL_get0_param_)(SSL *);
  int (*SSL_set1_host_)(SSL *, const char *);
  int (*X509_VERIFY_PARAM_set1_ip_asc_)(X509_VERIFY_PARAM *, const char *);
  unsigned long (*ERR_get_error_)(void);
  void (*ERR_error_string_n_)(unsigned long, char *, size_t);
  void (*ERR_clear_error_)(void);
  // OPTIONAL kTLS surface — bound with plain dlsym (never need(), which
  // aborts): SSL_sendfile exists only in OpenSSL 3.0+, and a 1.1 runtime
  // must still serve (callers null-check and fall back to the SSL_write
  // pump). BIO* is opaque void* here — only ever passed straight back
  // into BIO_ctrl.
  unsigned long (*SSL_set_options_)(SSL *, unsigned long);
  void *(*SSL_get_wbio_)(const SSL *);
  long (*BIO_ctrl_)(void *, int, long, void *);
  long (*SSL_sendfile_)(SSL *, int, long, size_t, int);
};

inline Api &api() {
  static Api a = [] {
    Api x = {};
    // same candidate order as sha256.h: OpenSSL 3 sonames, dev symlinks,
    // then the 1.1 soname (the whole surface below exists since 1.1.0)
    void *ssl = nullptr;
    for (const char *name : {"libssl.so.3", "libssl.so", "libssl.so.1.1"}) {
      if ((ssl = ::dlopen(name, RTLD_NOW | RTLD_GLOBAL)) != nullptr) break;
    }
    void *crypto = nullptr;
    for (const char *name : {"libcrypto.so.3", "libcrypto.so",
                             "libcrypto.so.1.1"}) {
      if ((crypto = ::dlopen(name, RTLD_NOW | RTLD_GLOBAL)) != nullptr) break;
    }
    if (!ssl || !crypto) {
      ::fprintf(stderr, "[demodel-tpu] fatal: cannot dlopen OpenSSL: %s\n",
                ::dlerror());
      ::abort();
    }
    auto need = [](void *h, const char *name) -> void * {
      void *s = ::dlsym(h, name);
      if (!s) {
        ::fprintf(stderr, "[demodel-tpu] fatal: missing OpenSSL symbol %s\n",
                  name);
        ::abort();
      }
      return s;
    };
#define DM_BIND(h, field, name) \
  x.field = reinterpret_cast<decltype(x.field)>(need(h, name))
    DM_BIND(ssl, TLS_server_method_, "TLS_server_method");
    DM_BIND(ssl, TLS_client_method_, "TLS_client_method");
    DM_BIND(ssl, SSL_CTX_new_, "SSL_CTX_new");
    DM_BIND(ssl, SSL_CTX_free_, "SSL_CTX_free");
    DM_BIND(ssl, SSL_CTX_use_certificate_chain_file_,
            "SSL_CTX_use_certificate_chain_file");
    DM_BIND(ssl, SSL_CTX_use_PrivateKey_file_, "SSL_CTX_use_PrivateKey_file");
    DM_BIND(ssl, SSL_CTX_check_private_key_, "SSL_CTX_check_private_key");
    DM_BIND(ssl, SSL_CTX_set_default_verify_paths_,
            "SSL_CTX_set_default_verify_paths");
    DM_BIND(ssl, SSL_CTX_load_verify_locations_,
            "SSL_CTX_load_verify_locations");
    DM_BIND(ssl, SSL_CTX_set_verify_, "SSL_CTX_set_verify");
    DM_BIND(ssl, SSL_new_, "SSL_new");
    DM_BIND(ssl, SSL_free_, "SSL_free");
    DM_BIND(ssl, SSL_set_fd_, "SSL_set_fd");
    DM_BIND(ssl, SSL_accept_, "SSL_accept");
    DM_BIND(ssl, SSL_connect_, "SSL_connect");
    DM_BIND(ssl, SSL_read_, "SSL_read");
    DM_BIND(ssl, SSL_pending_, "SSL_pending");
    DM_BIND(ssl, SSL_has_pending_, "SSL_has_pending");
    DM_BIND(ssl, SSL_write_, "SSL_write");
    DM_BIND(ssl, SSL_shutdown_, "SSL_shutdown");
    DM_BIND(ssl, SSL_get_error_, "SSL_get_error");
    DM_BIND(ssl, SSL_ctrl_, "SSL_ctrl");
    DM_BIND(ssl, SSL_get0_param_, "SSL_get0_param");
    DM_BIND(ssl, SSL_set1_host_, "SSL_set1_host");
    DM_BIND(crypto, X509_VERIFY_PARAM_set1_ip_asc_,
            "X509_VERIFY_PARAM_set1_ip_asc");
    DM_BIND(crypto, ERR_get_error_, "ERR_get_error");
    DM_BIND(crypto, ERR_error_string_n_, "ERR_error_string_n");
    DM_BIND(crypto, ERR_clear_error_, "ERR_clear_error");
#undef DM_BIND
    // nullable binds (see Api): absent symbols leave null pointers and
    // the writer plane degrades to the userspace SSL_write pump
    x.SSL_set_options_ = reinterpret_cast<decltype(x.SSL_set_options_)>(
        ::dlsym(ssl, "SSL_set_options"));
    x.SSL_get_wbio_ = reinterpret_cast<decltype(x.SSL_get_wbio_)>(
        ::dlsym(ssl, "SSL_get_wbio"));
    x.SSL_sendfile_ = reinterpret_cast<decltype(x.SSL_sendfile_)>(
        ::dlsym(ssl, "SSL_sendfile"));
    x.BIO_ctrl_ = reinterpret_cast<decltype(x.BIO_ctrl_)>(
        ::dlsym(crypto, "BIO_ctrl"));
    return x;
  }();
  return a;
}

}  // namespace dm_ssl

#define TLS_server_method (dm_ssl::api().TLS_server_method_)
#define TLS_client_method (dm_ssl::api().TLS_client_method_)
#define SSL_CTX_new (dm_ssl::api().SSL_CTX_new_)
#define SSL_CTX_free (dm_ssl::api().SSL_CTX_free_)
#define SSL_CTX_use_certificate_chain_file \
  (dm_ssl::api().SSL_CTX_use_certificate_chain_file_)
#define SSL_CTX_use_PrivateKey_file (dm_ssl::api().SSL_CTX_use_PrivateKey_file_)
#define SSL_CTX_check_private_key (dm_ssl::api().SSL_CTX_check_private_key_)
#define SSL_CTX_set_default_verify_paths \
  (dm_ssl::api().SSL_CTX_set_default_verify_paths_)
#define SSL_CTX_load_verify_locations \
  (dm_ssl::api().SSL_CTX_load_verify_locations_)
#define SSL_CTX_set_verify (dm_ssl::api().SSL_CTX_set_verify_)
#define SSL_new (dm_ssl::api().SSL_new_)
#define SSL_free (dm_ssl::api().SSL_free_)
#define SSL_set_fd (dm_ssl::api().SSL_set_fd_)
#define SSL_accept (dm_ssl::api().SSL_accept_)
#define SSL_connect (dm_ssl::api().SSL_connect_)
#define SSL_read (dm_ssl::api().SSL_read_)
#define SSL_pending (dm_ssl::api().SSL_pending_)
#define SSL_has_pending (dm_ssl::api().SSL_has_pending_)
#define SSL_write (dm_ssl::api().SSL_write_)
#define SSL_shutdown (dm_ssl::api().SSL_shutdown_)
#define SSL_get_error (dm_ssl::api().SSL_get_error_)
#define SSL_ctrl (dm_ssl::api().SSL_ctrl_)
#define SSL_get0_param (dm_ssl::api().SSL_get0_param_)
#define SSL_set1_host (dm_ssl::api().SSL_set1_host_)
#define X509_VERIFY_PARAM_set1_ip_asc \
  (dm_ssl::api().X509_VERIFY_PARAM_set1_ip_asc_)
#define ERR_get_error (dm_ssl::api().ERR_get_error_)
#define ERR_error_string_n (dm_ssl::api().ERR_error_string_n_)
#define ERR_clear_error (dm_ssl::api().ERR_clear_error_)

#define SSL_set_tlsext_host_name(s, name)                        \
  SSL_ctrl((s), DM_SSL_CTRL_SET_TLSEXT_HOSTNAME,                 \
           DM_TLSEXT_NAMETYPE_host_name,                         \
           reinterpret_cast<void *>(const_cast<char *>(name)))
